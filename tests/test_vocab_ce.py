"""Fused vocab projection + label-smoothed CE kernel
(ops/pallas/vocab_ce.py, run through the Pallas interpreter on CPU):
numerics vs the composed reference, gradients vs AD of the composition,
and the transformer use_fused_ce path training parity.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.ops.pallas.vocab_ce import fused_vocab_ce


def _ref_loss(h, w, labels, eps):
    z = (h @ w).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(z, axis=-1)
    zt = jnp.take_along_axis(z, labels[:, None], axis=-1)[:, 0]
    return lse - (1 - eps) * zt - (eps / w.shape[1]) * jnp.sum(z, -1)


@pytest.mark.parametrize("n,d,v,bt,bv", [
    (16, 8, 64, 8, 16),      # even blocks
    (10, 8, 50, 8, 16),      # ragged token AND vocab tails
    (4, 16, 33, 16, 32),     # single token block, ragged vocab
])
def test_fused_ce_matches_composition(n, d, v, bt, bv):
    h = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v),
                          jnp.float32) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
    for eps in (0.0, 0.1):
        ref = _ref_loss(h, w, labels, eps)
        got = fused_vocab_ce(h, w, labels, eps, bt, bv)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_fused_ce_gradients_match_ad():
    n, d, v = 12, 8, 40
    h = jax.random.normal(jax.random.PRNGKey(3), (n, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(4), (d, v),
                          jnp.float32) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(5), (n,), 0, v)
    cot = jax.random.normal(jax.random.PRNGKey(6), (n,), jnp.float32)

    def via_kernel(hh, ww):
        return jnp.sum(fused_vocab_ce(hh, ww, labels, 0.1, 8, 16) * cot)

    def via_ref(hh, ww):
        return jnp.sum(_ref_loss(hh, ww, labels, 0.1) * cot)

    gk = jax.grad(via_kernel, argnums=(0, 1))(h, w)
    gr = jax.grad(via_ref, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gk[0]), np.asarray(gr[0]),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gk[1]), np.asarray(gr[1]),
                               rtol=2e-4, atol=2e-5)


def test_fused_ce_leading_dims_and_bf16():
    h = jax.random.normal(jax.random.PRNGKey(7), (2, 6, 8),
                          jnp.bfloat16)
    w = (jax.random.normal(jax.random.PRNGKey(8), (8, 32),
                           jnp.bfloat16) * 0.1)
    labels = jax.random.randint(jax.random.PRNGKey(9), (2, 6), 0, 32)
    loss = fused_vocab_ce(h, w, labels, 0.1, 8, 16)
    assert loss.shape == (2, 6)
    ref = _ref_loss(h.reshape(-1, 8).astype(jnp.float32),
                    w.astype(jnp.float32),
                    labels.reshape(-1), 0.1).reshape(2, 6)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=0.05, atol=0.05)


def test_transformer_fused_ce_trains_and_matches_unfused():
    """use_fused_ce model: same loss trajectory as the one_hot
    composition (both are lse - (1-eps)z_t - (eps/V)sum_z) on a tiny
    config; the fused op must appear in the program."""
    from paddle_tpu.models import transformer

    def run(fused, steps=4):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            model = transformer.build_model(
                src_vocab_size=60, trg_vocab_size=60, max_length=8,
                n_layer=1, n_head=2, d_model=16, d_inner_hid=32,
                dropout=0.0, use_fused_ce=fused)
            if fused:
                types = [op.type for op in main.global_block().ops]
                assert "fused_vocab_softmax_ce" in types
            exe = fluid.Executor()
            exe.run(startup)
            feed = transformer.make_fake_batch(4, 8, 60, 60)
            losses = []
            for _ in range(steps):
                lv, = exe.run(main, feed=feed,
                              fetch_list=[model["loss"]])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    base = run(False)
    fused = run(True)
    # identical math, identical init (same seed/name sequence): the
    # trajectories track closely
    np.testing.assert_allclose(fused, base, rtol=2e-2, atol=2e-2)
    assert fused[-1] < fused[0]


def test_transformer_fused_options_shard_over_mp_mesh():
    """fused_qkv + fused CE under a dp×mp mesh: the head-grouped fused
    layout shards whole heads over mp (mp=4 | n_head=4), so the sharded
    trajectory must MATCH the unsharded one — not just run (VERDICT r3
    weak #6: the old concat layout only promised 'correct but
    resharded')."""
    from paddle_tpu.models import transformer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.strategies import megatron_transformer_rules

    def run(mesh):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 3
        scope = fluid.Scope()
        losses = []
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            model = transformer.build_model(
                src_vocab_size=64, trg_vocab_size=64, max_length=8,
                n_layer=1, n_head=4, d_model=32, d_inner_hid=64,
                dropout=0.0, use_fused_ce=True, fused_qkv=True)
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mesh is not None:
                bs = fluid.BuildStrategy()
                bs.sharding_rules = megatron_transformer_rules()
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=model["loss"].name, build_strategy=bs,
                    mesh=mesh)
            feed = transformer.make_fake_batch(8, 8, 64, 64)
            for _ in range(3):
                lv, = exe.run(prog, feed=feed,
                              fetch_list=[model["loss"]])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    sharded = run(make_mesh({"dp": 2, "mp": 4}))
    single = run(None)
    assert all(np.isfinite(sharded))
    assert sharded[-1] < sharded[0]
    np.testing.assert_allclose(sharded, single, rtol=1e-4, atol=1e-5)
