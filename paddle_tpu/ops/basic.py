"""Basic math / tensor ops.

Covers the reference's dense-math group (SURVEY.md §2.2 "Dense math" +
"Tensor manipulation" + "Reduce"): mul, matmul, scale, cast, sum, mean,
elementwise family with broadcast axis, comparisons, fill/assign/random
init ops, reshape/transpose/concat/split/etc.
(reference files: paddle/fluid/operators/mul_op.cc, matmul_op.cc,
elementwise/*, reduce_ops/*, fill_constant_op.cc, ...)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .common import broadcast_y, first, opt_in, out, to_jnp_dtype


# --------------------------------------------------------------------------
# Fill / init / random
# --------------------------------------------------------------------------

@register_op("fill_constant")
def fill_constant(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    return out(Out=jnp.full(shape, attrs.get("value", 0.0), dtype=dtype))


@register_op("fill_zeros_like")
def fill_zeros_like(ctx, ins, attrs):
    return out(Out=jnp.zeros_like(first(ins, "X")))


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(ctx, ins, attrs):
    x = first(ins, "Input")
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    return out(Out=jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dtype))


@register_op("assign")
def assign(ctx, ins, attrs):
    return out(Out=first(ins, "X"))


@register_op("assign_value")
def assign_value(ctx, ins, attrs):
    values = np.asarray(attrs["values"], dtype=attrs.get("dtype", "float32"))
    return out(Out=jnp.asarray(values.reshape(attrs["shape"])))


@register_op("gaussian_random")
def gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    x = jax.random.normal(ctx.rng(), shape, dtype=jnp.float32)
    x = x * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return out(Out=x.astype(dtype))


@register_op("uniform_random")
def uniform_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    x = jax.random.uniform(
        ctx.rng(), shape, dtype=jnp.float32,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0))
    return out(Out=x.astype(dtype))


@register_op("truncated_gaussian_random")
def truncated_gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    x = jax.random.truncated_normal(ctx.rng(), -2.0, 2.0, shape, jnp.float32)
    x = x * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return out(Out=x.astype(dtype))


@register_op("uniform_random_batch_size_like")
def uniform_random_batch_size_like(ctx, ins, attrs):
    x = first(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        x.shape[attrs.get("input_dim_idx", 0)]
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    o = jax.random.uniform(ctx.rng(), tuple(shape), dtype=jnp.float32,
                           minval=attrs.get("min", -1.0),
                           maxval=attrs.get("max", 1.0))
    return out(Out=o.astype(dtype))


@register_op("gaussian_random_batch_size_like")
def gaussian_random_batch_size_like(ctx, ins, attrs):
    """reference: operators/gaussian_random_batch_size_like_op.cc —
    N(mean, std) samples with the batch dim copied from Input."""
    x = first(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        x.shape[attrs.get("input_dim_idx", 0)]
    dtype = to_jnp_dtype(attrs.get("dtype", "float32"))
    o = (attrs.get("mean", 0.0)
         + attrs.get("std", 1.0)
         * jax.random.normal(ctx.rng(), tuple(shape), jnp.float32))
    return out(Out=o.astype(dtype))


@register_op("randint")
def randint(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    x = jax.random.randint(ctx.rng(), shape, attrs.get("low", 0),
                           attrs.get("high", 2**31 - 1),
                           dtype=to_jnp_dtype(attrs.get("dtype", "int64")))
    return out(Out=x)


# --------------------------------------------------------------------------
# Matmul family
# --------------------------------------------------------------------------

@register_op("mul")
def mul(ctx, ins, attrs):
    """Flattening matmul (reference: operators/mul_op.cc) — x flattened to 2D
    at x_num_col_dims, y at y_num_col_dims."""
    x, y = first(ins, "X"), first(ins, "Y")
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xnc])), int(np.prod(xs[xnc:]))))
    y2 = y.reshape((int(np.prod(ys[:ync])), int(np.prod(ys[ync:]))))
    o = x2 @ y2
    return out(Out=o.reshape(xs[:xnc] + ys[ync:]))


@register_op("matmul")
def matmul(ctx, ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    o = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        o = o * alpha
    return out(Out=o)


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx, ins, attrs):
    x, y, w = first(ins, "X"), first(ins, "Y"), first(ins, "Weight")
    bias = opt_in(ins, "Bias")
    o = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if bias is not None:
        o = o + bias
    return out(Out=o)


# --------------------------------------------------------------------------
# Elementwise family (with fluid broadcast-axis semantics)
# --------------------------------------------------------------------------

def _register_elementwise(name, fn, out_dtype=None):
    @register_op(name)
    def impl(ctx, ins, attrs, _fn=fn, _dt=out_dtype):
        x, y = first(ins, "X"), first(ins, "Y")
        y = broadcast_y(x, y, attrs.get("axis", -1))
        o = _fn(x, y)
        if _dt is not None:
            o = o.astype(_dt)
        return out(Out=o)


_register_elementwise("elementwise_add", jnp.add)
_register_elementwise("elementwise_sub", jnp.subtract)
_register_elementwise("elementwise_mul", jnp.multiply)
_register_elementwise("elementwise_div", jnp.divide)
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_pow", jnp.power)
_register_elementwise("elementwise_mod", jnp.mod)
_register_elementwise("elementwise_floordiv", jnp.floor_divide)
_register_elementwise("less_than", jnp.less, "bool")
_register_elementwise("less_equal", jnp.less_equal, "bool")
_register_elementwise("greater_than", jnp.greater, "bool")
_register_elementwise("greater_equal", jnp.greater_equal, "bool")
_register_elementwise("equal", jnp.equal, "bool")
_register_elementwise("not_equal", jnp.not_equal, "bool")


@register_op("logical_and")
def logical_and(ctx, ins, attrs):
    return out(Out=jnp.logical_and(first(ins, "X"), first(ins, "Y")))


@register_op("logical_or")
def logical_or(ctx, ins, attrs):
    return out(Out=jnp.logical_or(first(ins, "X"), first(ins, "Y")))


@register_op("logical_xor")
def logical_xor(ctx, ins, attrs):
    return out(Out=jnp.logical_xor(first(ins, "X"), first(ins, "Y")))


@register_op("logical_not")
def logical_not(ctx, ins, attrs):
    return out(Out=jnp.logical_not(first(ins, "X")))


# --------------------------------------------------------------------------
# Scale / cast / clip / sign-style unary
# --------------------------------------------------------------------------

@register_op("scale")
def scale(ctx, ins, attrs):
    x = first(ins, "X")
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        o = x * s + b
    else:
        o = (x + b) * s
    return out(Out=o.astype(x.dtype))


@register_op("cast")
def cast(ctx, ins, attrs):
    return out(Out=first(ins, "X").astype(to_jnp_dtype(attrs["out_dtype"])))


@register_op("clip")
def clip(ctx, ins, attrs):
    return out(Out=jnp.clip(first(ins, "X"), attrs["min"], attrs["max"]))


@register_op("clip_by_norm")
def clip_by_norm(ctx, ins, attrs):
    x = first(ins, "X")
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(x * x))
    scale_f = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                        1.0)
    return out(Out=x * scale_f)


@register_op("isfinite")
def isfinite(ctx, ins, attrs):
    # reference isfinite_op reduces to a single bool over all inputs
    xs = ins["X"]
    flags = [jnp.all(jnp.isfinite(x.astype(jnp.float32))) for x in xs]
    res = flags[0]
    for f in flags[1:]:
        res = jnp.logical_and(res, f)
    return out(Out=res.reshape((1,)))


# --------------------------------------------------------------------------
# Reductions / sum / mean
# --------------------------------------------------------------------------

@register_op("sum")
def sum_op(ctx, ins, attrs):
    """Sum a list of tensors (reference: operators/sum_op.cc) — used by
    backward grad accumulation and lr scheduling."""
    xs = ins["X"]
    o = xs[0]
    for x in xs[1:]:
        o = o + x
    return out(Out=o)


@register_op("mean")
def mean(ctx, ins, attrs):
    return out(Out=jnp.mean(first(ins, "X")).reshape((1,)))


def _register_reduce(name, fn):
    @register_op(name)
    def impl(ctx, ins, attrs, _fn=fn):
        x = first(ins, "X")
        if attrs.get("reduce_all", False):
            axes = None
        else:
            axes = tuple(a if a >= 0 else a + x.ndim
                         for a in attrs.get("dim", [0]))
        o = _fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        if o.ndim == 0:
            o = o.reshape((1,))
        return out(Out=o)


_register_reduce("reduce_sum", jnp.sum)
_register_reduce("reduce_mean", jnp.mean)
_register_reduce("reduce_max", jnp.max)
_register_reduce("reduce_min", jnp.min)
_register_reduce("reduce_prod", jnp.prod)
_register_reduce("reduce_all", jnp.all)
_register_reduce("reduce_any", jnp.any)


# --------------------------------------------------------------------------
# Shape manipulation
# --------------------------------------------------------------------------

@register_op("reshape")
def reshape(ctx, ins, attrs):
    x = first(ins, "X")
    shape = list(attrs["shape"])
    # fluid reshape: 0 means copy dim from input, -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    o = x.reshape(tuple(shape))
    return {"Out": [o], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("squeeze")
def squeeze(ctx, ins, attrs):
    x = first(ins, "X")
    axes = attrs.get("axes", [])
    if axes:
        o = jnp.squeeze(x, axis=tuple(a if a >= 0 else a + x.ndim
                                      for a in axes))
    else:
        o = jnp.squeeze(x)
    return {"Out": [o], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("unsqueeze")
def unsqueeze(ctx, ins, attrs):
    x = first(ins, "X")
    o = x
    for a in sorted(attrs["axes"]):
        o = jnp.expand_dims(o, a)
    return {"Out": [o], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("flatten")
def flatten(ctx, ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    o = x.reshape((lead, -1))
    return {"Out": [o], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("transpose")
def transpose(ctx, ins, attrs):
    x = first(ins, "X")
    o = jnp.transpose(x, attrs["axis"])
    return {"Out": [o], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("concat")
def concat(ctx, ins, attrs):
    return out(Out=jnp.concatenate(ins["X"], axis=attrs.get("axis", 0)))


@register_op("split")
def split(ctx, ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        pieces = jnp.split(x, idx, axis=axis)
    else:
        pieces = jnp.split(x, num, axis=axis)
    return {"Out": list(pieces)}


@register_op("stack")
def stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def unstack(ctx, ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    pieces = [jnp.squeeze(p, axis=axis)
              for p in jnp.split(x, n, axis=axis)]
    return {"Y": pieces}


@register_op("expand")
def expand(ctx, ins, attrs):
    x = first(ins, "X")
    times = attrs["expand_times"]
    return out(Out=jnp.tile(x, tuple(times)))


@register_op("slice")
def slice_op(ctx, ins, attrs):
    x = first(ins, "Input")
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = s + dim if s < 0 else min(s, dim)
        e = e + dim if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return out(Out=x[tuple(idx)])


@register_op("gather")
def gather(ctx, ins, attrs):
    x, index = first(ins, "X"), first(ins, "Index")
    return out(Out=jnp.take(x, index.reshape(-1), axis=0))


@register_op("batched_gather")
def batched_gather(ctx, ins, attrs):
    """Per-row gather (batch_dims=1): X (N, A, ...) + Index (N, S) →
    (N, S, ...).  TPU-native helper for the fixed-slot detection
    sampling ops (rpn_target_assign gathers predictions at sampled
    anchor slots); no direct fluid analog — the reference gathered on
    flattened LoD rows instead."""
    x, index = first(ins, "X"), first(ins, "Index")
    idx = index.astype(jnp.int32)
    idx_exp = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return out(Out=jnp.take_along_axis(
        x, jnp.broadcast_to(idx_exp, idx.shape + x.shape[2:]), axis=1))


@register_op("scatter")
def scatter(ctx, ins, attrs):
    x, ids, updates = first(ins, "X"), first(ins, "Ids"), first(ins, "Updates")
    ids = ids.reshape(-1)
    if attrs.get("overwrite", True):
        o = x.at[ids].set(updates)
    else:
        o = x.at[ids].add(updates)
    return out(Out=o)


@register_op("minus")
def minus(ctx, ins, attrs):
    """reference: operators/minus_op.cc — Out = X - Y (no broadcast
    axis; the reference grad maker is scale(-1), jax AD matches)."""
    return out(Out=first(ins, "X") - first(ins, "Y"))


@register_op("is_empty")
def is_empty(ctx, ins, attrs):
    """reference: operators/is_empty_op.cc — (1,) bool, true iff the
    tensor has zero elements (same (1,) scalar convention as
    array_length / max_sequence_len).  Shapes are static under XLA so
    this folds to a constant at trace time."""
    x = first(ins, "X")
    return out(Out=jnp.asarray([x.size == 0], dtype=jnp.bool_))


@register_op("cos_sim")
def cos_sim(ctx, ins, attrs):
    """reference: operators/cos_sim_op.cc — row-wise cosine similarity
    over all non-batch dims; Y's batch dim may be 1 (broadcast).
    Outputs Out (N, 1) plus the XNorm/YNorm intermediates the reference
    exposes for its grad kernel (jax AD doesn't need them, but parity
    tests read them)."""
    x, y = first(ins, "X"), first(ins, "Y")
    xf = x.reshape(x.shape[0], -1)
    yf = y.reshape(y.shape[0], -1)
    eps = 1e-12
    xn = jnp.sqrt(jnp.sum(xf * xf, axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(yf * yf, axis=1, keepdims=True))
    dot = jnp.sum(xf * yf, axis=1, keepdims=True)
    o = dot / jnp.maximum(xn * yn, eps)
    return out(Out=o, XNorm=xn, YNorm=yn)


@register_op("pad_constant_like")
def pad_constant_like(ctx, ins, attrs):
    """reference: operators/pad_constant_like_op.cc — pad Y at the HIGH
    edge of every axis up to X's shape; Out.shape == X.shape."""
    x, y = first(ins, "X"), first(ins, "Y")
    if x.ndim != y.ndim:
        raise ValueError(
            f"pad_constant_like: rank mismatch {x.ndim} vs {y.ndim}")
    cfg = [(0, xd - yd) for xd, yd in zip(x.shape, y.shape)]
    if any(b < 0 for _, b in cfg):
        raise ValueError(
            f"pad_constant_like: X dims {x.shape} must be >= Y dims "
            f"{y.shape}")
    o = jnp.pad(y, cfg, constant_values=attrs.get("pad_value", 0.0))
    return out(Out=o)


@register_op("pad")
def pad(ctx, ins, attrs):
    x = first(ins, "X")
    paddings = attrs["paddings"]
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return out(Out=jnp.pad(x, cfg, constant_values=attrs.get("pad_value", 0.0)))


@register_op("reverse")
def reverse(ctx, ins, attrs):
    x = first(ins, "X")
    o = x
    for a in attrs["axis"]:
        o = jnp.flip(o, axis=a)
    return out(Out=o)


@register_op("shape")
def shape_op(ctx, ins, attrs):
    x = first(ins, "Input")
    return out(Out=jnp.asarray(x.shape, dtype=jnp.int32))


@register_op("one_hot")
def one_hot(ctx, ins, attrs):
    x = first(ins, "X")
    depth = attrs["depth"]
    o = jax.nn.one_hot(x.reshape(x.shape[:-1]) if x.shape[-1] == 1 else x,
                       depth, dtype=jnp.float32)
    return out(Out=o)


@register_op("top_k")
def top_k(ctx, ins, attrs):
    x = first(ins, "X")
    k = attrs["k"]
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int32)]}


@register_op("argsort")
def argsort(ctx, ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": [jnp.sort(x, axis=axis)], "Indices": [idx.astype(jnp.int32)]}


@register_op("arg_max")
def arg_max(ctx, ins, attrs):
    x = first(ins, "X")
    return out(Out=jnp.argmax(x, axis=attrs.get("axis", -1)).astype(jnp.int32))


@register_op("arg_min")
def arg_min(ctx, ins, attrs):
    x = first(ins, "X")
    return out(Out=jnp.argmin(x, axis=attrs.get("axis", -1)).astype(jnp.int32))


@register_op("cumsum")
def cumsum(ctx, ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", -1)
    o = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        o = o - x
    if attrs.get("reverse", False):
        o = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive", False):
            o = o - x
    return out(Out=o)


@register_op("increment")
def increment(ctx, ins, attrs):
    x = first(ins, "X")
    return out(Out=x + jnp.asarray(attrs.get("step", 1.0), dtype=x.dtype))


@register_op("range")
def range_op(ctx, ins, attrs):
    start = first(ins, "Start").reshape(())
    end = first(ins, "End").reshape(())
    step = first(ins, "Step").reshape(())
    num = attrs.get("num")  # static length required under jit
    if num is None:
        raise ValueError("range op requires static 'num' attr under XLA")
    o = start + step * jnp.arange(num, dtype=start.dtype)
    return out(Out=o)


@register_op("multiplex")
def multiplex(ctx, ins, attrs):
    ids = first(ins, "Ids").reshape(-1)
    xs = jnp.stack(ins["X"], axis=0)
    rows = jnp.arange(ids.shape[0])
    return out(Out=xs[ids, rows])


@register_op("where_op")
def where_op(ctx, ins, attrs):
    cond = first(ins, "Condition")
    x, y = first(ins, "X"), first(ins, "Y")
    return out(Out=jnp.where(cond, x, y))
