"""CompiledProgram: multi-device compilation of a Program.

reference: python/paddle/fluid/compiler.py:33 CompiledProgram
.with_data_parallel (the forward-looking API wrapping ParallelExecutor,
parallel_executor.cc:191).  Instead of cloning per-device SSA graphs and
inserting NCCL all-reduce handles, the single traced program is jitted
with NamedShardings: feeds sharded over the batch ("dp") axis, params
replicated (AllReduce mode) or sharded (Reduce/FSDP mode, or tensor-
parallel rules) — XLA GSPMD partitions the computation and inserts the
ICI collectives, including the gradient all-reduce.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..core.executor import RNG_STATE_VAR, interpret_program
from ..core.program import Program
from .mesh import get_default_mesh
from .strategies import ShardingRules


class ReduceStrategy:
    AllReduce = 0  # replicated params, grads all-reduced (GSPMD-implicit)
    Reduce = 1     # FSDP-style: params sharded over dp


class BuildStrategy:
    """reference: framework/details/build_strategy.h:55."""

    ReduceStrategy = ReduceStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.sharding_rules: Optional[ShardingRules] = None
        self.memory_optimize = False  # XLA buffer liveness subsumes this
        self.enable_inplace = True
        # multi-trainer (multi-host) topology; wired to jax.distributed by
        # parallel/dist.py init_distributed (reference: nccl2 mode,
        # parallel_executor.cc:254 num_trainers*ndev ranks)
        self.num_trainers = 1
        self.trainer_id = 0
        # K-micro-batch gradient accumulation (reference:
        # ir/multi_batch_merge_pass.cc)
        self.gradient_accumulation_steps = 1
        # GPipe microbatch count for programs built with
        # fluid.pipeline_scope() layer tagging, executed on a mesh with
        # a "pp" axis.  0 = auto (2x the pp degree when the batch
        # divides, else the pp degree).  Ignored when the program has no
        # pipeline tags or the mesh has no pp axis.
        self.pipeline_microbatches = 0
        # Opt-in explicit gradient synchronization for dp
        # (strategies.GradSyncConfig or a mode string): None keeps the
        # implicit GSPMD all-reduce; "int8" routes dense grads through
        # the blockwise-quantized two-phase exchange
        # (collectives.quantized_all_reduce, EQuARX), "bf16" the same
        # explicit path without quantization (the A/B control arm).
        self.grad_sync = None


class ExecutionStrategy:
    """reference: framework/details/execution_strategy.h (inert knobs kept
    for API parity; XLA owns scheduling)."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1


class CompiledProgram:
    def __init__(self, program: Program):
        self._program = program
        self._mesh = None
        self._batch_axis = "dp"
        self._rules: Optional[ShardingRules] = None
        self._cache: Dict[Any, Any] = {}
        self._loss_name = None
        self._accum_steps = 1
        self._pp_microbatches = 0
        self._aot_cache: Dict[Any, Any] = {}
        self._opt_names = None  # lazy: optimizer-state var names

    def with_data_parallel(self, loss_name: Optional[str] = None,
                           build_strategy: Optional[BuildStrategy] = None,
                           exec_strategy: Optional[ExecutionStrategy] = None,
                           share_vars_from=None, places=None,
                           mesh=None, batch_axis: str = "dp"):
        self._loss_name = loss_name
        self._mesh = mesh or get_default_mesh()
        self._batch_axis = batch_axis
        bs = build_strategy or BuildStrategy()
        self._accum_steps = int(getattr(bs, "gradient_accumulation_steps",
                                        1) or 1)
        self._pp_microbatches = int(getattr(bs, "pipeline_microbatches",
                                            0) or 0)
        if bs.sharding_rules is not None:
            self._rules = bs.sharding_rules
        elif bs.reduce_strategy == ReduceStrategy.Reduce:
            self._rules = ShardingRules(default="fsdp",
                                        fsdp_axis=batch_axis)
        else:
            self._rules = ShardingRules()
        from .strategies import GradSyncConfig

        # explicit grad-sync mode rides the PROGRAM (the executor's
        # interpret_program hook reads it at trace time; the mesh/axis
        # come from the executing_mesh context this wrapper sets)
        self._program._grad_sync = GradSyncConfig.normalize(
            getattr(bs, "grad_sync", None))
        self._program._compiled_wrapper = self
        return self

    # -- shardings -------------------------------------------------------
    def _optimizer_state_names(self) -> set:
        """Names of the program's optimizer-state vars (accumulators,
        pow counters, the lr var) — the set the ZeRO axis shards.  Uses
        the same op-slot classification as observe.memory's buckets so
        the sharded bytes and the reported optimizer_state bucket are
        the SAME population."""
        if self._opt_names is None:
            from ..observe.memory import _program_var_buckets

            _params, opt = _program_var_buckets(self._program)
            self._opt_names = opt
        return self._opt_names

    def state_spec_for(self, name: str, shape) -> tuple:
        """The PartitionSpec dims this wrapper assigns to a STATE var:
        the rule spec, with the ZeRO axis composed in for
        optimizer-state vars (strategies.opt_state_spec_for).  Public
        because io.load_sharded reshards checkpoints into exactly these
        specs (mesh-shape-agnostic load)."""
        if name in self._optimizer_state_names():
            return self._rules.opt_state_spec_for(name, shape,
                                                  self._mesh)
        return self._rules.spec_for(name, shape, self._mesh)

    def data_axes(self) -> tuple:
        """Mesh axes the batch shards over (batch axis + fsdp/ZeRO)."""
        return self._rules.data_axes_for(self._mesh, self._batch_axis)

    def _state_sharding(self, name: str, value):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..observe import metrics as _obs_metrics

        if name == RNG_STATE_VAR or name == _obs_metrics.TELEMETRY_VAR:
            # the telemetry accumulator is a dict pytree of scalars: a
            # single replicated sharding acts as a pytree prefix
            return NamedSharding(self._mesh, P())
        spec = self.state_spec_for(name, np.shape(value))
        return NamedSharding(self._mesh, P(*spec))

    def _feed_sharding(self, name, value):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # the data-axis rule lives on ShardingRules (feed_spec_for):
        # dim 0 over the batch axis when divisible, explicit rules win,
        # meshes without the batch axis (pure {"sp": N}) replicate
        spec = self._rules.feed_spec_for(name, np.shape(value),
                                         self._mesh,
                                         batch_axis=self._batch_axis)
        return NamedSharding(self._mesh, P(*spec))

    # -- execution -------------------------------------------------------
    def run(self, executor, feed: Dict[str, Any], fetch_names, scope,
            return_numpy: bool = True, iterations: int = 1,
            accumulation_steps: int = 1):
        import jax

        fn, state, feed_arrays, _, _ = self._prepare_step(
            feed, fetch_names, scope, iterations, accumulation_steps)
        new_state, fetches = fn(state, feed_arrays)
        for name, val in new_state.items():
            scope.set_var(name, val)
        from ..core.executor import _debug_checks

        _debug_checks(fetch_names, fetches, new_state)
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        return fetches

    def compiled_hlo_text(self, feed: Dict[str, Any], fetch_names,
                          scope, iterations: int = 1) -> str:
        """AOT-lower the sharded step and return the compiled
        (post-SPMD-partitioning) HLO text — for inspecting which
        collectives GSPMD inserted (e.g. asserting MoE dispatch lowers
        to all-to-all, tests/test_moe.py) and for roofline tooling.
        One extra XLA compile; the traced fn comes from the same
        cache as run()."""
        return self.compiled_step(feed, fetch_names, scope,
                                  iterations=iterations).as_text()

    def compiled_step(self, feed: Dict[str, Any], fetch_names=(),
                      scope=None, iterations: int = 1,
                      with_names: bool = False):
        """AOT-compile the SHARDED step and return the jax Compiled
        object — the multi-device analog of Executor.compiled_step.
        This is what the dp bench's comm accounting reads: the
        post-SPMD module's collective instructions land in
        observe.cost's `comm` bucket (all-reduce/all-gather/
        reduce-scatter/all-to-all/collective-permute), so
        `comm_bytes` comes from the SAME analytic accounting as every
        other bucket.  Memoized per (feed signature, fetches,
        iterations) — bench's comm fields reuse one compile.

        with_names=True returns (compiled, arg_names) like
        Executor.compiled_step: the per-entry-parameter
        ("state"|"feed", var_name) labels observe.memory uses to
        attribute PER-DEVICE buffer bytes to named state vars — how
        the fsdp A/B proves opt-state bytes actually dropped on the
        sharded step."""
        from ..core.executor import global_scope

        fn, state, feed_arrays, _, _ = self._prepare_step(
            feed, list(fetch_names), scope or global_scope(),
            iterations, 1)
        key = (self._program._uid, self._program._version,
               tuple(sorted(feed)), tuple(fetch_names), iterations,
               tuple((n, tuple(getattr(v, "shape", ()) or ()),
                      str(getattr(v, "dtype", type(v).__name__)))
                     for n, v in sorted(feed_arrays.items())))
        entry = self._aot_cache.get(key)
        if entry is None:
            from ..observe.memory import _arg_labels

            compiled = fn.lower(state, feed_arrays).compile()
            entry = (compiled,
                     _arg_labels(state, feed_arrays, compiled=compiled))
            self._aot_cache[key] = entry
        return entry if with_names else entry[0]

    def _prepare_step(self, feed, fetch_names, scope, iterations,
                      accumulation_steps):
        import jax

        # an explicit per-run override wins over the BuildStrategy knob
        accum = (accumulation_steps if accumulation_steps != 1
                 else self._accum_steps)

        if self._mesh is None:
            # bare CompiledProgram(program): single-device compilation,
            # like fluid without with_data_parallel
            from .mesh import make_mesh

            self._mesh = make_mesh({"dp": 1})
            if self._rules is None:
                self._rules = ShardingRules()

        program = self._program
        block = program.global_block()
        if RNG_STATE_VAR not in scope.vars:
            scope.set_var(RNG_STATE_VAR,
                          jax.random.PRNGKey(program.random_seed))
        state_names = tuple(sorted(
            v.name for v in block.vars.values()
            if v.persistable and scope.has_var(v.name)))
        from ..observe import metrics as _obs_metrics

        telemetry = getattr(program, "_telemetry_enabled", False)
        if telemetry:
            # mirror Executor._prepare: the device-side accumulator
            # rides the (donated) state pytree so enable_telemetry()
            # works identically under a mesh — bench dp entries carry
            # the same honesty counters as single-device ones (and the
            # same numerics fields when the program opted in)
            tel_cur = scope.find_var(_obs_metrics.TELEMETRY_VAR)
            if tel_cur is None:
                scope.set_var(_obs_metrics.TELEMETRY_VAR,
                              _obs_metrics.init_telemetry_for(program))
            else:
                patched = _obs_metrics.ensure_numerics_fields(
                    program, tel_cur)
                if patched is not tel_cur:
                    scope.set_var(_obs_metrics.TELEMETRY_VAR, patched)
            state_names = state_names + (_obs_metrics.TELEMETRY_VAR,)
        feed_shardings = {n: self._feed_sharding(n, v)
                          for n, v in feed.items()}
        # the chosen feed shardings are part of the key: a final partial
        # batch that is no longer dp-divisible must recompile with a
        # replicated layout rather than reuse the sharded executable
        feed_sig = tuple(sorted(
            (n, str(s.spec)) for n, s in feed_shardings.items()))
        key = (program._uid, program._version, feed_sig,
               tuple(fetch_names), state_names, id(self._mesh), iterations,
               accum)
        entry = self._cache.get(key)

        state = {n: scope.find_var(n) for n in state_names}
        state[RNG_STATE_VAR] = scope.find_var(RNG_STATE_VAR)

        if entry is None:
            state_shardings = {n: self._state_sharding(n, v)
                               for n, v in state.items()}
            persistable_names = tuple(sorted(
                v.name for v in block.vars.values() if v.persistable))

            feed_names = tuple(sorted(feed))

            def step(st, feeds):
                from .mesh import executing_mesh

                rng_key = st[RNG_STATE_VAR]
                env = {k: v for k, v in st.items() if k != RNG_STATE_VAR}
                env.update(feeds)
                with executing_mesh(
                        self._mesh, batch_axis=self._batch_axis,
                        pipeline_microbatches=self._pp_microbatches):
                    env = interpret_program(program, env, rng_key,
                                            fetch_names=fetch_names,
                                            accum_steps=accum,
                                            feed_names=feed_names)
                new_state = {n: env[n] for n in persistable_names
                             if n in env}
                from ..observe.metrics import TELEMETRY_VAR

                if TELEMETRY_VAR in env:
                    # executor-private state (not a block var): threads
                    # the step + chain_iterations carry, same as the
                    # single-device step fn
                    new_state[TELEMETRY_VAR] = env[TELEMETRY_VAR]
                new_state[RNG_STATE_VAR] = jax.random.split(rng_key, 1)[0]
                fetches = [env[n] for n in fetch_names]
                return new_state, fetches

            from ..core.executor import chain_iterations

            fn = jax.jit(
                chain_iterations(step, iterations),
                in_shardings=(state_shardings, feed_shardings),
                # pin the updated state to the SAME shardings it came
                # in with: without this XLA may infer a different
                # (replicated) output layout for ZeRO-sharded optimizer
                # state, which silently breaks donation — per-device
                # opt-state bytes then DOUBLE (input + undonated
                # output) and an all-gather sneaks into every step
                out_shardings=(state_shardings, None),
                donate_argnums=(0,),
            )
            entry = (fn, state_shardings, feed_shardings)
            self._cache[key] = entry

        fn, state_shardings, feed_shardings = entry
        # place inputs according to shardings (no-op when already placed)
        state = {n: jax.device_put(v, state_shardings[n])
                 for n, v in state.items()}
        import jax.numpy as jnp

        feed_arrays = {n: jax.device_put(jnp.asarray(v), feed_shardings[n])
                       for n, v in feed.items()}
        return fn, state, feed_arrays, state_shardings, feed_shardings
