"""Sequence/context parallelism: ring attention + Ulysses.

The reference has NO long-context machinery (SURVEY.md §5.7 marks this
an explicit capability gap: its long-sequence story was LoD no-padding
batching).  These are the TPU-native fills:

- **Ring attention**: q/k/v sharded over the sequence axis; k/v shards
  rotate around the ICI ring via collective-permute while each device
  accumulates attention for its local queries with online-softmax
  merging.  Memory per device is O(T/P); compute overlaps communication
  around the ring.
- **Ulysses**: all-to-all exchanges sequence sharding for head sharding,
  runs dense local attention (the Pallas flash kernel), and exchanges
  back.  One a2a pair instead of P-1 permutes; needs H divisible by P.

Both are differentiable (pure jax + collectives) and tested against
single-device full attention on the virtual CPU mesh.

Operand layouts (matching ops/attention.py): layout="nhtd" takes
(N, H, T, D); layout="nthd" + n_head takes the head-major head-grouped
(N, T, H*D) contract — T is then dim 1, the shard axis moves with it,
and the per-chunk logsumexp statistic rides (N, T_local, H) so merging
broadcasts against the grouped output without a transpose.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _local_attention_with_lse(q, k, v, q_off, k_off, scale, causal,
                              layout="nhtd", n_head=None):
    """Chunk attention returning (o, lse); positions are global offsets
    so causal masking works across rotated chunks.
    nhtd: q (N, H, Tq, D), k/v (N, H, Tk, D), lse (N, H, Tq).
    nthd: q (N, Tq, H*D), k/v (N, Tk, H*D), lse (N, Tq, H)."""
    if layout == "nthd":
        n, t_q, hd = q.shape
        d = hd // n_head
        q4 = q.reshape(n, t_q, n_head, d)
        k4 = k.reshape(n, k.shape[1], n_head, d)
        v4 = v.reshape(n, v.shape[1], n_head, d)
        s = jnp.einsum("nqhd,nkhd->nhqk", q4, k4).astype(jnp.float32) \
            * scale
    else:
        s = jnp.einsum("nhqd,nhkd->nhqk", q, k).astype(jnp.float32) \
            * scale
    if causal:
        t_q_, t_k_ = s.shape[-2], s.shape[-1]
        q_pos = q_off + jnp.arange(t_q_)[:, None]
        k_pos = k_off + jnp.arange(t_k_)[None, :]
        s = jnp.where(q_pos >= k_pos, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    # guard fully-masked rows
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    lse = (m_safe + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]  # (N,H,Tq)
    if layout == "nthd":
        o4 = jnp.einsum("nhqk,nkhd->nqhd",
                        (p / jnp.maximum(l, 1e-30)).astype(q.dtype), v4)
        return o4.reshape(q.shape), jnp.moveaxis(lse, 1, 2)  # (N,Tq,H)
    o = jnp.einsum("nhqk,nhkd->nhqd", p.astype(q.dtype), v)
    o = o / jnp.maximum(l, 1e-30).astype(o.dtype)
    return o, lse  # (N,H,Tq,D), (N,H,Tq)


def _merge(o_a, lse_a, o_b, lse_b, head_shape=None):
    """Combine two normalized partial attentions via their logsumexps.
    head_shape: for the nthd layout the grouped (..., H*D) outputs view
    as (..., H, D) so the per-(N,T,H) weights broadcast; None keeps the
    nhtd elementwise form."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)[..., None]
    wb = jnp.exp(lse_b - m)[..., None]
    oa, ob = o_a, o_b
    if head_shape is not None:
        oa = o_a.reshape(o_a.shape[:-1] + head_shape)
        ob = o_b.reshape(o_b.shape[:-1] + head_shape)
    o = (oa.astype(jnp.float32) * wa + ob.astype(jnp.float32) * wb) / \
        (wa + wb)
    if head_shape is not None:
        o = o.reshape(o_a.shape)
    lse = m + jnp.log(wa[..., 0] + wb[..., 0])
    return o.astype(o_a.dtype), lse


def ring_attention(q, k, v, mesh, axis: str = "sp", scale=None,
                   causal: bool = False, use_pallas=None,
                   batch_axis=None, layout: str = "nhtd", n_head=None):
    """q/k/v: GLOBAL (N, H, T, D) — or (N, T, H*D) with layout="nthd"
    + n_head — logically sharded over T on `axis`.  Returns the full
    attention output with the same sharding.

    use_pallas: route each rotated chunk through the tiled Pallas flash
    kernel (forward AND backward O(t_local) memory, causal masking via
    the kernel's global-offset scalars).  Default: auto (on for TPU).
    batch_axis: mesh axis the batch dim is sharded over (e.g. "dp" on a
    dp x sp mesh) — without it the shard_map boundary would all-gather
    dp-sharded activations and every dp group would redo the compute."""
    from .collectives import compat_shard_map

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    n_dev = mesh.shape[axis]
    t_axis = 1 if layout == "nthd" else 2
    if layout == "nthd":
        if not n_head:
            raise ValueError("ring_attention layout='nthd' needs n_head")
        head_shape = (n_head, q.shape[-1] // n_head)
        if scale is None:
            scale = head_shape[1] ** -0.5
    else:
        head_shape = None
        if scale is None:
            scale = q.shape[-1] ** -0.5
    t_total = q.shape[t_axis]
    t_local = t_total // n_dev
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def chunk_attn(q_l, k_cur, v_cur, q_off, k_off):
        if use_pallas:
            from ..ops.pallas.flash_attention import pallas_flash_attention

            return pallas_flash_attention(
                q_l, k_cur, v_cur, scale=scale, causal=causal,
                q_offset=q_off, k_offset=k_off, return_lse=True,
                layout=layout, n_head=n_head)
        return _local_attention_with_lse(q_l, k_cur, v_cur, q_off, k_off,
                                         scale, causal, layout=layout,
                                         n_head=n_head)

    def local_fn(q_l, k_l, v_l):
        idx = jax.lax.axis_index(axis)
        q_off = idx * t_local

        def body(j, carry):
            o, lse, k_cur, v_cur = carry
            # chunk j originated on device (idx - j) mod n_dev
            src = (idx - j) % n_dev
            k_off = src * t_local
            o_j, lse_j = chunk_attn(q_l, k_cur, v_cur, q_off, k_off)
            o, lse = _merge(o, lse, o_j, lse_j, head_shape=head_shape)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return o, lse, k_nxt, v_nxt

        o0 = jnp.zeros_like(q_l)
        if layout == "nthd":
            lse0 = jnp.full(q_l.shape[:-1] + (head_shape[0],), -1e30,
                            jnp.float32)
        else:
            lse0 = jnp.full(q_l.shape[:-1], -1e30, jnp.float32)
        o, lse, _, _ = jax.lax.fori_loop(
            0, n_dev, body, (o0, lse0, k_l, v_l))
        return o

    b_ax = (batch_axis if batch_axis
            and mesh.shape.get(batch_axis, 1) > 1 else None)
    if layout == "nthd":
        spec = P(b_ax, axis, None)
    else:
        spec = P(b_ax, None, axis, None)
    fn = compat_shard_map(local_fn, mesh, (spec, spec, spec), spec)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh, axis: str = "sp", scale=None,
                      causal: bool = False, use_pallas=None,
                      batch_axis=None, layout: str = "nhtd",
                      n_head=None):
    """Ulysses sequence parallelism: a2a seq→heads, dense local
    attention, a2a heads→seq.  q/k/v: GLOBAL (N, H, T, D) — or
    (N, T, H*D) head-grouped with layout="nthd" + n_head — sharded over
    T on `axis`; H must be divisible by the axis size (the grouped
    minor dim splits into whole heads, so the a2a chunks are
    head-aligned).  use_pallas None = auto (Pallas kernel on TPU), same
    convention as ring_attention; batch_axis keeps dp-sharded batches
    sharded inside the shard_map."""
    from .collectives import compat_shard_map

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    n_dev = mesh.shape[axis]
    if layout == "nthd":
        if not n_head:
            raise ValueError("ulysses_attention layout='nthd' needs "
                             "n_head")
        h, d = n_head, q.shape[-1] // n_head
        seq_axis, head_axis = 1, 2
    else:
        n, h, t, d = q.shape
        seq_axis, head_axis = 2, 1
    if h % n_dev != 0:
        raise ValueError(f"Ulysses needs heads ({h}) divisible by "
                         f"mesh axis {axis!r} size ({n_dev})")
    if scale is None:
        scale = d ** -0.5

    def local_fn(q_l, k_l, v_l):
        def seq_to_heads(x):
            # nhtd: (N, H, T/P, D) -> (N, H/P, T, D)
            # nthd: (N, T/P, H*D) -> (N, T, (H/P)*D) — the grouped
            # minor dim splits on whole-head boundaries (H % P == 0)
            return jax.lax.all_to_all(x, axis, split_axis=head_axis,
                                      concat_axis=seq_axis, tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=seq_axis,
                                      concat_axis=head_axis, tiled=True)

        qh, kh, vh = seq_to_heads(q_l), seq_to_heads(k_l), seq_to_heads(v_l)
        local_heads = h // n_dev
        if use_pallas:
            from ..ops.pallas.flash_attention import pallas_flash_attention

            oh = pallas_flash_attention(qh, kh, vh, scale=scale,
                                        causal=causal, layout=layout,
                                        n_head=(local_heads
                                                if layout == "nthd"
                                                else None))
        else:
            oh, _ = _local_attention_with_lse(
                qh, kh, vh, 0, 0, scale, causal, layout=layout,
                n_head=local_heads if layout == "nthd" else None)
        return heads_to_seq(oh)

    b_ax = (batch_axis if batch_axis
            and mesh.shape.get(batch_axis, 1) > 1 else None)
    if layout == "nthd":
        spec = P(b_ax, axis, None)
    else:
        spec = P(b_ax, None, axis, None)
    fn = compat_shard_map(local_fn, mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
