"""ResNet for cifar10 and ImageNet (ResNet-50).

reference: benchmark/fluid/models/resnet.py (resnet_cifar10,
resnet_imagenet with bottleneck blocks).  bf16-friendly: convs/matmuls
run in the param dtype; batch-norm stats accumulate in f32 inside the op.

data_format="NHWC" (build_model kwarg) runs the whole conv stack
channels-last — the TPU-preferred layout (the lane dimension wants the
feature axis minor); the feed stays NCHW like the reference and is
transposed once at the front of the graph.
"""

from __future__ import annotations

from .. import layers, optimizer


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_train=True, data_format="NCHW"):
    conv1 = layers.conv2d(input=input, filter_size=filter_size,
                          num_filters=ch_out, stride=stride,
                          padding=padding, act=None, bias_attr=False,
                          data_format=data_format)
    return layers.batch_norm(input=conv1, act=act, is_test=not is_train,
                             data_layout=data_format)


def shortcut(input, ch_out, stride, is_train=True, data_format="NCHW"):
    ch_in = input.shape[1 if data_format == "NCHW" else 3]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             is_train=is_train, data_format=data_format)
    return input


def basicblock(input, ch_out, stride, is_train=True, data_format="NCHW"):
    short = shortcut(input, ch_out, stride, is_train=is_train,
                     data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_train=is_train,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None,
                          is_train=is_train, data_format=data_format)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride, is_train=True, data_format="NCHW"):
    short = shortcut(input, ch_out * 4, stride, is_train=is_train,
                     data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_train=is_train,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_train=is_train,
                          data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_train=is_train, data_format=data_format)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, is_train=True,
               data_format="NCHW"):
    res_out = block_func(input, ch_out, stride, is_train=is_train,
                         data_format=data_format)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_train=is_train,
                             data_format=data_format)
    return res_out


def resnet_imagenet(input, class_dim, depth=50, is_train=True,
                    data_format="NCHW"):
    cfg = {
        18: ([2, 2, 2, 2], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_train=is_train,
                          data_format=data_format)
    pool1 = layers.pool2d(input=conv1, pool_type="max", pool_size=3,
                          pool_stride=2, pool_padding=1,
                          data_format=data_format)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_train,
                      data_format)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_train,
                      data_format)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_train,
                      data_format)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_train,
                      data_format)
    pool2 = layers.pool2d(input=res4, pool_type="avg", global_pooling=True,
                          pool_size=7, data_format=data_format)
    out = layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim, depth=32, is_train=True,
                   data_format="NCHW"):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input=input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_train=is_train,
                          data_format=data_format)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_train, data_format)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_train, data_format)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_train, data_format)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         global_pooling=True, data_format=data_format)
    out = layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def build_model(dataset="flowers", depth=50, class_dim=1000,
                learning_rate=0.01, with_optimizer=True, is_train=True,
                use_amp=False, data_format="NCHW"):
    """reference benchmark/fluid/models/resnet.py get_model."""
    if dataset == "cifar10":
        dshape = [3, 32, 32]
        model = resnet_cifar10
        class_dim = 10
        depth = 32
    else:
        dshape = [3, 224, 224]
        model = resnet_imagenet
    input = layers.data(name="data", shape=dshape, dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    if data_format == "NHWC":
        # feed contract stays NCHW (reference); one transpose at the
        # graph edge keeps the whole conv stack channels-last
        input = layers.transpose(input, perm=[0, 2, 3, 1])
    predict = model(input, class_dim, depth=depth, is_train=is_train,
                    data_format=data_format)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    batch_acc = layers.accuracy(input=predict, label=label)
    if with_optimizer:
        opt = optimizer.MomentumOptimizer(learning_rate=learning_rate,
                                          momentum=0.9)
        if use_amp:
            from .. import amp as amp_mod

            opt = amp_mod.decorate(opt)
        opt.minimize(avg_cost)
    return {"loss": avg_cost, "accuracy": batch_acc,
            "feeds": ["data", "label"], "predict": predict}
