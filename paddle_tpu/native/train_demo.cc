// Train-from-C++ demo: a non-Python entrypoint for the framework.
//
// TPU-native analog of the reference's C++ train demo
// (reference: paddle/fluid/train/demo/demo_trainer.cc — load a saved
// ProgramDesc, run the startup program once, then iterate the main
// program from C++ without the python CLI).  The compute engine here is
// JAX/XLA, which is hosted by libpython, so the deployment shape is:
// embed the interpreter via the CPython C API (the environment's
// sanctioned binding path — no pybind), drive the same
// Program/Executor API a python entry would, and surface losses to the
// C++ side through the C API.
//
// Build + run:
//   sh paddle_tpu/native/build_demo.sh     # links against libpython
//   ./paddle_tpu/native/train_demo [steps]
// Prints "step K loss=..." lines and exits 0 on a decreasing loss.

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>

namespace {

const char* kDriver = R"PY(
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers

def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[16, 13], append_batch_size=False)
        y = layers.data('y', shape=[16, 1], append_batch_size=False)
        pred = layers.fc(x, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    return exe, main, loss

_exe, _main, _loss = build()
_rng = np.random.RandomState(0)
_w = _rng.rand(13, 1).astype('float32')

def train_step():
    xv = _rng.rand(16, 13).astype('float32')
    yv = xv @ _w
    (lv,) = _exe.run(_main, feed={'x': xv, 'y': yv},
                     fetch_list=[_loss])
    return float(np.asarray(lv).reshape(()))
)PY";

double call_train_step(PyObject* globals) {
  PyObject* result =
      PyRun_String("train_step()", Py_eval_input, globals, globals);
  if (result == nullptr) {
    PyErr_Print();
    std::exit(2);
  }
  double loss = PyFloat_AsDouble(result);
  Py_DECREF(result);
  return loss;
}

}  // namespace

int main(int argc, char** argv) {
  int steps = argc > 1 ? std::atoi(argv[1]) : 20;

  Py_Initialize();
  PyObject* main_module = PyImport_AddModule("__main__");  // borrowed
  PyObject* globals = PyModule_GetDict(main_module);       // borrowed

  // repo root on sys.path so `import paddle_tpu` resolves when the demo
  // runs from the build tree
  PyRun_SimpleString(
      "import os, sys\n"
      "sys.path.insert(0, os.path.dirname(os.path.dirname(\n"
      "    os.path.dirname(os.path.abspath('paddle_tpu/native')))))\n"
      "sys.path.insert(0, os.getcwd())\n");

  if (PyRun_String(kDriver, Py_file_input, globals, globals) == nullptr) {
    PyErr_Print();
    Py_Finalize();
    return 2;
  }

  double first_loss = 0.0, last_loss = 0.0;
  for (int i = 0; i < steps; ++i) {
    last_loss = call_train_step(globals);
    if (i == 0) first_loss = last_loss;
    std::printf("step %d loss=%.6f\n", i, last_loss);
  }
  Py_Finalize();

  if (!(last_loss < first_loss)) {
    std::fprintf(stderr, "loss did not decrease: %f -> %f\n", first_loss,
                 last_loss);
    return 1;
  }
  std::printf("train_demo ok: loss %.6f -> %.6f\n", first_loss, last_loss);
  return 0;
}
