"""Composite neural-net layers.

reference: python/paddle/fluid/layers/nn.py (9726 LoC, ~180 layer
functions).  Each function creates output vars + parameters via LayerHelper
and appends OpDescs to the default main program; shapes/dtypes are inferred
abstractly (core/shape_inference.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.desc import normalize_dtype
from ..core.program import Variable
from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from . import tensor as tensor_layers


# ---------------------------------------------------------------------------
# Core dense layers
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (reference layers/nn.py fc) — mul + sum +
    bias + activation."""
    helper = LayerHelper("fc", name=name, act=act, bias_attr=bias_attr,
                         input=input)
    inputs = input if isinstance(input, list) else [input]
    dtype = inputs[0].dtype

    mul_results = []
    for inp in inputs:
        in_shape = inp.shape
        param_shape = [
            int(np.prod([abs(d) for d in in_shape[num_flatten_dims:]])),
            size,
        ]
        w = helper.create_parameter(param_attr, shape=param_shape,
                                    dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul", inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims,
                   "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    result = helper.append_activation(pre_act)
    if num_flatten_dims >= 2 and not isinstance(input, list):
        # sequence-preserving projection: keep the seq_len companion
        from .sequence import _propagate_seq_len

        _propagate_seq_len(input, result)
    return result


def switch_moe(input, num_experts, d_inner, top_k=1,
               capacity_factor=1.25, act="relu", param_attr=None,
               bias_attr=None, name=None):
    """Mixture-of-Experts FFN block (ops/moe.py) with expert
    parallelism: per-expert weights are (E, D, H)/(E, H, D) with the E
    axis sharded over the mesh's mp/ep axis (the `moe_expert` name
    matches the expert sharding rule in parallel/strategies.py; GSPMD
    inserts the GShard all-to-alls).  Returns (out, aux_loss,
    fraction): add `aux_weight * aux_loss` to the objective for load
    balancing; fetch `fraction` (E,) for per-expert routing
    observability.

    Not in the 1.2 reference (predates MoE); first-class here because
    ep is a primary TPU scale axis."""
    from ..param_attr import ParamAttr

    for attr in (param_attr, bias_attr):
        if isinstance(attr, ParamAttr) and attr.name:
            raise ValueError(
                "switch_moe: a NAMED ParamAttr cannot apply to its "
                "multiple parameters (name collision) and would break "
                "the moe_expert/moe_gate prefix the ep sharding rules "
                "key on; use name= to disambiguate layers instead")
    d = int(input.shape[-1])
    # user names APPEND to the moe_gate/moe_expert prefixes — the
    # prefixes are what the ep sharding rules key on, so a named layer
    # must still match them
    gate_h = LayerHelper("moe_gate",
                         name=name and f"moe_gate_{name}")
    dtype = input.dtype
    gate_w = gate_h.create_parameter(param_attr, shape=[d, num_experts],
                                     dtype=dtype)
    eh = LayerHelper("moe_expert",
                     name=name and f"moe_expert_{name}")
    # explicit per-expert fans: the default rank-3 fan computation
    # treats (E, D, H) as a conv kernel and under-initializes ~sqrt(E)x
    from ..initializer import Xavier

    w1 = eh.create_parameter(param_attr, shape=[num_experts, d, d_inner],
                             dtype=dtype,
                             default_initializer=Xavier(
                                 fan_in=d, fan_out=d_inner))
    b1 = eh.create_parameter(bias_attr, shape=[num_experts, d_inner],
                             dtype=dtype, is_bias=True)
    w2 = eh.create_parameter(param_attr, shape=[num_experts, d_inner, d],
                             dtype=dtype,
                             default_initializer=Xavier(
                                 fan_in=d_inner, fan_out=d))
    b2 = eh.create_parameter(bias_attr, shape=[num_experts, d],
                             dtype=dtype, is_bias=True)
    out_v = eh.create_variable_for_type_inference(dtype)
    aux = eh.create_variable_for_type_inference("float32")
    frac = eh.create_variable_for_type_inference("float32")
    eh.append_op(
        type="moe_ffn",
        inputs={"X": [input], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out_v], "AuxLoss": [aux], "Fraction": [frac]},
        attrs={"top_k": top_k, "capacity_factor": capacity_factor,
               "act": act})
    out_v.desc.shape = tuple(input.shape)
    aux.desc.shape = (1,)
    frac.desc.shape = (num_experts,)
    return out_v, aux, frac


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference layers/nn.py embedding → lookup_table op.  is_sparse /
    is_distributed are accepted for parity; on TPU the table is a dense
    sharded array and sparse grads become dense segment-sums (see
    parallel/ for table sharding)."""
    helper = LayerHelper("embedding", name=None)
    w = helper.create_parameter(param_attr, shape=size, dtype=dtype,
                                default_initializer=Xavier())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lookup_table", inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [out]},
        attrs={"padding_idx": -1 if padding_idx is None else padding_idx,
               "is_sparse": bool(is_sparse)})
    from .sequence import _propagate_seq_len

    _propagate_seq_len(input, out)
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y,
                            "alpha": float(alpha)})
    return out


# ---------------------------------------------------------------------------
# Elementwise / scale / clip
# ---------------------------------------------------------------------------

def elementwise_op(op_type, x, y, axis=-1, act=None, name=None,
                   out_dtype=None):
    helper = LayerHelper(op_type, name=name, act=act)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_mod", x, y, axis, act, name)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_floordiv", x, y, axis, act, name)


def greater_equal(x, y):
    return elementwise_op("greater_equal", x, y, out_dtype="bool")


# less_than / less_equal / greater_than / equal / not_equal and the
# logical_* family live in layers/control_flow.py (as in fluid) with the
# cond=/out= write-into-var form that While loops need.


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"max_norm": float(max_norm)})
    return out


# ---------------------------------------------------------------------------
# Conv / pool / norm
# ---------------------------------------------------------------------------

def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """reference layers/nn.py conv2d — NCHW; data_format="NHWC" runs
    channels-last (TPU-preferred layout; filters stay OIHW)."""
    helper = LayerHelper("conv2d", name=name, act=act, bias_attr=bias_attr)
    dtype = input.dtype
    groups = groups or 1
    c_axis = 1 if data_format == "NCHW" else 3
    num_channels = input.shape[c_axis]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    fan_in = (num_channels // groups) * int(np.prod(filter_size))
    std = (2.0 / fan_in) ** 0.5
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype,
                                default_initializer=Normal(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups,
               "data_format": data_format})
    pre_act = helper.append_bias_op(pre_bias, dim_start=c_axis)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    dtype = input.dtype
    if filter_size is None:
        raise ValueError("filter_size required (output_size-only inference "
                         "not yet supported)")
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    num_channels = input.shape[1]
    filter_shape = [num_channels, num_filters // (groups or 1)] + \
        list(filter_size)
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups or 1})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference layers/nn.py conv3d_transpose — NCDHW, filter
    (C_in, C_out/groups, kD, kH, kW)."""
    helper = LayerHelper("conv3d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    dtype = input.dtype
    if filter_size is None:
        raise ValueError("filter_size required (output_size-only inference "
                         "not yet supported)")
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    num_channels = input.shape[1]
    filter_shape = [num_channels, num_filters // (groups or 1)] + \
        list(filter_size)
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups or 1})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1)
    return helper.append_activation(pre_act)


def cos_sim(X, Y):
    """reference layers/nn.py:1187 — row-wise cosine similarity,
    Y's batch dim broadcastable."""
    helper = LayerHelper("cos_sim")
    o = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [o], "XNorm": [xn], "YNorm": [yn]})
    return o


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference layers/nn.py pad_constant_like — pad y up to x's shape
    at the high edges."""
    helper = LayerHelper("pad_constant_like", name=name)
    o = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(type="pad_constant_like",
                     inputs={"X": [x], "Y": [y]}, outputs={"Out": [o]},
                     attrs={"pad_value": float(pad_value)})
    return o


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Distillation CTR loss (public Paddle op; absent from the 1.2
    reference tree — see ops/nn.py for the label encoding)."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    o = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="teacher_student_sigmoid_loss",
        inputs={"X": [input], "Label": [label]}, outputs={"Y": [o]},
        attrs={"soft_max_up_bound": float(soft_max_up_bound),
               "soft_max_lower_bound": float(soft_max_lower_bound)})
    return o


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", name=name, act=act, bias_attr=bias_attr)
    dtype = input.dtype
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    filter_shape = [num_filters, input.shape[1] // groups] + list(filter_size)
    w = helper.create_parameter(param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None,
           data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _pair(pool_size),
               "strides": _pair(pool_stride), "paddings": _pair(pool_padding),
               "global_pooling": global_pooling, "exclusive": exclusive,
               "data_format": data_format})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               use_global_stats=False):
    """reference layers/nn.py batch_norm — creates Scale/Bias params and
    persistable moving Mean/Variance updated in-place by the op."""
    helper = LayerHelper("batch_norm", name=name, act=act)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    shape = [c]
    scale_var = helper.create_parameter(
        param_attr, shape=shape, dtype=dtype,
        default_initializer=Constant(1.0))
    bias_var = helper.create_parameter(
        ParamAttr._to_attr(bias_attr) or ParamAttr(), shape=shape,
        dtype=dtype, is_bias=True)
    mean = helper.create_or_get_global_variable(
        moving_mean_name or f"{helper.name}.mean", shape, dtype,
        initializer=Constant(0.0))
    variance = helper.create_or_get_global_variable(
        moving_variance_name or f"{helper.name}.var", shape, dtype,
        initializer=Constant(1.0))
    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype)
    saved_var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale_var], "Bias": [bias_var],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [y], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean], "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(y)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = input.dtype
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(param_attr, shape=norm_shape, dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            ParamAttr._to_attr(bias_attr) or ParamAttr(), shape=norm_shape,
            dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    m = helper.create_variable_for_type_inference(dtype)
    v = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [m], "Variance": [v]},
                     attrs={"begin_norm_axis": begin_norm_axis,
                            "epsilon": epsilon})
    return helper.append_activation(y)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", name=name, act=act)
    dtype = input.dtype
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(param_attr, shape=[c], dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(ParamAttr._to_attr(bias_attr) or
                                    ParamAttr(), shape=[c], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    m = helper.create_variable_for_type_inference(dtype)
    v = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="group_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [m], "Variance": [v]},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(y)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="l2_normalize", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="dropout", inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "dropout_implementation": dropout_implementation})
    return out


# ---------------------------------------------------------------------------
# Softmax / losses
# ---------------------------------------------------------------------------

def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, label_smooth_eps=0.0):
    """label_smooth_eps > 0 folds label smoothing into the hard-label CE,
    mathematically identical to one_hot → label_smooth → soft-label CE.
    Convenience/API form; on TPU the one_hot composition benchmarks
    slightly faster (XLA fuses it onto the MXU), so prefer that on hot
    paths — see models/transformer.py."""
    helper = LayerHelper("softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    sm = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Loss": [loss], "Softmax": [sm]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index,
                            "label_smooth_eps": float(label_smooth_eps)})
    if return_softmax:
        return loss, sm
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square_error_cost",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    loss = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        ins["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        ins["OutsideWeight"] = [outside_weight]
    helper.append_op(type="smooth_l1_loss", inputs=ins,
                     outputs={"Out": [loss], "Diff": [diff]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    loss = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="huber_loss",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [loss], "Residual": [residual]},
                     attrs={"delta": delta})
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [loss]}, attrs={"epsilon": epsilon})
    return loss


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": [label]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=ins,
                     outputs={"Out": [out]}, attrs={"epsilon": epsilon})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="kldiv_loss",
                     inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [loss]},
                     attrs={"reduction": reduction})
    return loss


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# Reductions / shape manipulation
# ---------------------------------------------------------------------------

def _reduce(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"reduce_all": True, "dim": [0], "keep_dim": keep_dim}
    else:
        attrs = {"reduce_all": False,
                 "dim": dim if isinstance(dim, (list, tuple)) else [dim],
                 "keep_dim": keep_dim}
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_all", input, dim, keep_dim, name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_any", input, dim, keep_dim, name)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="squeeze", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="unsqueeze", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="flatten", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": axis})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "sections": [], "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs}, attrs=attrs)
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(type="stack", inputs={"X": x}, outputs={"Y": [out]},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def batched_gather(input, index, name=None):
    """Per-row gather: input (N, A, ...) gathered at index (N, S) →
    (N, S, ...) (used by rpn_target_assign; see ops/basic.py)."""
    helper = LayerHelper("batched_gather", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="batched_gather",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="pad2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value)})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="shape", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    return values, indices


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="cumsum", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis, "exclusive": exclusive,
                            "reverse": reverse})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    helper = LayerHelper("interpolate", name=name)
    if out_shape is None:
        h = int(input.shape[2] * scale)
        w = int(input.shape[3] * scale)
    else:
        h, w = out_shape
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="interpolate", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"out_h": int(h), "out_w": int(w),
                            "interp_method": resample.lower(),
                            "align_corners": bool(align_corners),
                            "align_mode": int(align_mode)})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "BILINEAR")


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "NEAREST")


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(param_attr, shape=alpha_shape,
                                    dtype=x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"groups": groups})
    return out


def space_to_depth(x, blocksize, name=None):
    helper = LayerHelper("space_to_depth", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="space_to_depth", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"blocksize": blocksize})
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def flash_attention(q, k, v, bias=None, scale=None, causal=False,
                    use_pallas=None, sequence_parallel=False,
                    layout="nhtd", n_head=None, name=None):
    """Fused multi-head attention over (N, H, T, D) tensors (see
    ops/attention.py).  The TPU-native replacement for composing
    matmul+softmax+matmul by hand.  layout="nthd" + n_head takes the
    head-major head-grouped (N, T, H*D) contract instead — what the
    attn_qkv projection emits directly, so NOTHING transposes at the
    kernel boundary (the ISSUE 8 layout).  With sequence_parallel=True
    (or "ring" / "ulysses") and a CompiledProgram mesh that has an `sp`
    axis, the sequence dimension shards over sp and attention runs as
    ring attention (KV ppermute rotation) or Ulysses (head/sequence
    all-to-all; needs sp | n_head) — the long-context path; causal/
    no-bias only."""
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    ins = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        ins["Bias"] = [bias]
    attrs = {"causal": causal, "use_pallas": use_pallas,
             "sequence_parallel": sequence_parallel,
             "layout": layout}
    if n_head is not None:
        attrs["n_head"] = int(n_head)
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type="flash_attention", inputs=ins,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def paged_attention(q, k_cache, v_cache, page_table, lengths, n_head,
                    scale=None, use_pallas=None, k_scale=None,
                    v_scale=None, name=None):
    """Decode-step ragged paged attention (ops/paged_kv.py): one query
    token per slot (Q (S, H*D) head-grouped) attends over that slot's
    K/V pages of the shared (P, page, H*D) pools, addressed through the
    (S, max_pages) page table and masked to `lengths`.  use_pallas
    routes to the tiled kernel (ops/pallas/paged_attention.py); the
    default XLA dense-gather twin is the layout-matched CPU/parity
    fallback.  k_scale/v_scale: (P, page, 1) sidecar pools for int8
    caches."""
    helper = LayerHelper("paged_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    ins = {"Q": [q], "KCache": [k_cache], "VCache": [v_cache],
           "PageTable": [page_table], "Lengths": [lengths]}
    if k_scale is not None:
        ins["KScale"] = [k_scale]
        ins["VScale"] = [v_scale]
    attrs = {"n_head": int(n_head), "use_pallas": use_pallas}
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type="paged_attention", inputs=ins,
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def _paged_write(op_type, k, v, k_cache, v_cache, page_table, extra_ins,
                 k_scale, v_scale, name):
    helper = LayerHelper(op_type, name=name)
    kc_out = helper.create_variable_for_type_inference(k_cache.dtype)
    vc_out = helper.create_variable_for_type_inference(v_cache.dtype)
    ins = {"K": [k], "V": [v], "KCache": [k_cache], "VCache": [v_cache],
           "PageTable": [page_table]}
    ins.update(extra_ins)
    outs = {"KCacheOut": [kc_out], "VCacheOut": [vc_out]}
    if k_scale is not None:
        ins["KScale"] = [k_scale]
        ins["VScale"] = [v_scale]
        ks_out = helper.create_variable_for_type_inference(k_scale.dtype)
        vs_out = helper.create_variable_for_type_inference(v_scale.dtype)
        outs["KScaleOut"] = [ks_out]
        outs["VScaleOut"] = [vs_out]
    helper.append_op(type=op_type, inputs=ins, outputs=outs)
    if k_scale is not None:
        return kc_out, vc_out, ks_out, vs_out
    return kc_out, vc_out


def paged_kv_write(k, v, k_cache, v_cache, page_table, write_pos,
                   active=None, k_scale=None, v_scale=None, name=None):
    """Commit ONE token's K/V per slot into the paged pools at
    `write_pos` (the decode-step write; ops/paged_kv.py).  Functional:
    returns the updated pools (+ scale sidecars for int8 caches);
    inactive slots (active 0) write nothing."""
    extra = {"WritePos": [write_pos]}
    if active is not None:
        extra["Active"] = [active]
    return _paged_write("paged_kv_write", k, v, k_cache, v_cache,
                        page_table, extra, k_scale, v_scale, name)


def paged_kv_prefill_write(k, v, k_cache, v_cache, page_table, seq_len,
                           k_scale=None, v_scale=None, name=None):
    """Commit a whole prompt's K/V (S, T, H*D) into the paged pools
    (the prefill-on-join write; ops/paged_kv.py).  Positions past
    seq_len[s] — all of them for a non-joining slot with seq_len 0 —
    are dropped."""
    return _paged_write("paged_kv_prefill_write", k, v, k_cache,
                        v_cache, page_table, {"SeqLen": [seq_len]},
                        k_scale, v_scale, name)


def speculative_accept(drafts, predictions, draft_len, active=None,
                       name=None):
    """Greedy longest-accepted-prefix acceptance (ops/paged_kv.py):
    Drafts (S, k) vs the verify forward's argmax Predictions (S, k+1),
    ragged per-slot draft lengths riding the DraftLen (S,) companion.
    Returns (accepted (S,) int32 [-1 for inactive slots], tokens
    (S, k+1) int32 [-1 padding]) — accepted+1 committed tokens per
    active slot, bit-identical to the sequential engine's stream."""
    helper = LayerHelper("speculative_accept", name=name)
    accepted = helper.create_variable_for_type_inference("int32")
    tokens = helper.create_variable_for_type_inference("int32")
    ins = {"Drafts": [drafts], "Predictions": [predictions],
           "DraftLen": [draft_len]}
    if active is not None:
        ins["Active"] = [active]
    helper.append_op(type="speculative_accept", inputs=ins,
                     outputs={"Accepted": [accepted],
                              "Tokens": [tokens]})
    return accepted, tokens


def add_position_encoding_at(x, position, alpha=1.0, beta=1.0,
                             name=None):
    """X (S, D) + sinusoidal encoding at one position per row — the
    decode-step twin of add_position_encoding (same formula), so a
    decoded token sees exactly the encoding its position would have had
    inside a prefill."""
    helper = LayerHelper("add_position_encoding_at", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="add_position_encoding_at",
                     inputs={"X": [x], "Position": [position]},
                     outputs={"Out": [out]},
                     attrs={"alpha": float(alpha), "beta": float(beta)})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32", min=-1.0,
                                   max=1.0, input_dim_idx=0,
                                   output_dim_idx=0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="uniform_random_batch_size_like",
        inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": normalize_dtype(dtype),
               "min": min, "max": max, "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": normalize_dtype(dtype),
                            "mean": mean, "std": std})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": normalize_dtype(dtype),
                            "min": min, "max": max})
    return out


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


def _triple(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v, v]


# ---------------------------------------------------------------------------
# Structured-prediction / sampling losses (ops/structured.py)
# reference: layers/nn.py nce:4023, hsigmoid:4171, warpctc:3646,
# edit_distance:3566, sampling_id:7712; layers.linear_chain_crf /
# crf_decoding live in fluid layers/nn.py:1453,1510.
# ---------------------------------------------------------------------------

def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """is_sparse is accepted for parity: on TPU the NCE weight grad stays
    dense (only the sampled rows receive nonzero gradient anyway, and the
    class count is the sampled-softmax small regime)."""
    helper = LayerHelper("nce", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_total_classes, dim],
                                dtype=input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    if bias_attr is not False:
        b = helper.create_parameter(
            ParamAttr._to_attr(bias_attr) or ParamAttr(),
            shape=[num_total_classes], dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    sampler_id = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}[sampler]
    if custom_dist is not None:
        import numpy as _np

        from .tensor import assign as _assign

        dist = _assign(_np.asarray(custom_dist, dtype="float32"))
        inputs["CustomDistProbs"] = [dist]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(input.dtype)
    sample_labels = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sample_logits],
                 "SampleLabels": [sample_labels]},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": int(num_neg_samples or 10),
               "sampler": sampler_id, "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    helper = LayerHelper("hierarchical_sigmoid", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, shape=[num_classes - 1, dim],
                                dtype=input.dtype)
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            ParamAttr._to_attr(bias_attr) or ParamAttr(),
            shape=[num_classes - 1], dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [cost], "PreOut": [pre_out]},
                     attrs={"num_classes": int(num_classes)})
    return cost


def linear_chain_crf(input, label, param_attr=None):
    """input: padded emissions (B, T, N) with a `.seq_len` companion."""
    from .sequence import seq_len_var

    helper = LayerHelper("linear_chain_crf")
    num_tags = input.shape[-1]
    transition = helper.create_parameter(
        param_attr, shape=[num_tags + 2, num_tags], dtype=input.dtype)
    sl = seq_len_var(input)
    if sl is None:
        raise ValueError("linear_chain_crf input needs a .seq_len "
                         "companion (declare data with lod_level=1)")
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition],
                "Label": [label], "SeqLen": [sl]},
        outputs={"LogLikelihood": [ll], "Alpha": [alpha]})
    return ll


def crf_decoding(input, param_attr, label=None):
    from .sequence import _propagate_seq_len, seq_len_var

    helper = LayerHelper("crf_decoding")
    if isinstance(param_attr, Variable):
        transition = param_attr
    else:
        attr = ParamAttr._to_attr(param_attr)
        block = helper.main_program.global_block()
        if attr.name and block.has_var(attr.name):
            transition = block.var(attr.name)
        else:
            # decode-only program: declare the (trained) transition param
            # so the scope value binds by name, as fluid does when the
            # decode net is built separately from the train net
            num_tags = input.shape[-1]
            transition = helper.create_parameter(
                attr, shape=[num_tags + 2, num_tags], dtype=input.dtype)
    sl = seq_len_var(input)
    if sl is None:
        raise ValueError("crf_decoding input needs a .seq_len companion")
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": [input], "Transition": [transition], "SeqLen": [sl]}
    if label is not None:
        ins["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=ins,
                     outputs={"ViterbiPath": [path]})
    _propagate_seq_len(input, path)
    return path


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  name=None):
    """input/label: padded id sequences (B, T) with .seq_len companions."""
    from .sequence import seq_len_var

    helper = LayerHelper("edit_distance", name=name)
    hl, rl = seq_len_var(input), seq_len_var(label)
    if hl is None or rl is None:
        raise ValueError("edit_distance needs .seq_len companions on both "
                         "input and label")
    dist = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance",
        inputs={"Hyps": [input], "Refs": [label], "HypsLen": [hl],
                "RefsLen": [rl]},
        outputs={"Out": [dist], "SequenceNum": [seq_num]},
        attrs={"normalized": bool(normalized),
               "ignored_tokens": list(ignored_tokens or [])})
    return dist, seq_num


def warpctc(input, label, blank=0, norm_by_times=False):
    """input: padded logits (B, T, C) w/ .seq_len; label: padded ids
    (B, U) w/ .seq_len."""
    from .sequence import seq_len_var

    helper = LayerHelper("warpctc")
    ll = seq_len_var(input)
    ul = seq_len_var(label)
    if ll is None or ul is None:
        raise ValueError("warpctc needs .seq_len companions on logits "
                         "and label")
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input], "Label": [label], "LogitsLen": [ll],
                "LabelLen": [ul]},
        outputs={"Loss": [loss]},
        attrs={"blank": int(blank), "norm_by_times": bool(norm_by_times)})
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    """argmax over classes then ctc_align (reference layers/nn.py
    ctc_greedy_decoder:3704). input: (B, T, C) probs w/ .seq_len."""
    from .sequence import _propagate_seq_len, seq_len_var

    helper = LayerHelper("ctc_greedy_decoder", name=name)
    ids = tensor_layers.argmax(input, axis=-1)
    sl = seq_len_var(input)
    decoded = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int32")
    ins = {"Input": [ids]}
    if sl is not None:
        ins["SeqLen"] = [sl]
    helper.append_op(type="ctc_align", inputs=ins,
                     outputs={"Output": [decoded], "OutLen": [out_len]},
                     attrs={"blank": int(blank), "merge_repeated": True})
    return decoded, out_len


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"seed": seed})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_len=None):
    """Chunk-level P/R/F1 for sequence tagging (reference layers/nn.py
    chunk_eval; IOB scheme).  input/label: (B, T) padded tag ids with a
    .seq_len companion on `input` (or pass seq_len=)."""
    from .sequence import seq_len_var

    if chunk_scheme != "IOB":
        raise NotImplementedError(
            f"chunk_scheme {chunk_scheme!r}: only IOB is implemented "
            f"(reference chunk_eval_op.h also supports IOE/IOBES)")
    helper = LayerHelper("chunk_eval")
    sl = seq_len if seq_len is not None else seq_len_var(input)
    if sl is None:
        raise ValueError("chunk_eval needs a .seq_len companion or "
                         "seq_len= argument")
    outs = {}
    for slot, dtype in [("Precision", "float32"), ("Recall", "float32"),
                        ("F1-Score", "float32"),
                        ("NumInferChunks", "int64"),
                        ("NumLabelChunks", "int64"),
                        ("NumCorrectChunks", "int64")]:
        outs[slot] = [helper.create_variable_for_type_inference(dtype)]
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label], "SeqLen": [sl]},
        outputs=outs,
        attrs={"num_chunk_types": int(num_chunk_types),
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    return (outs["Precision"][0], outs["Recall"][0], outs["F1-Score"][0],
            outs["NumInferChunks"][0], outs["NumLabelChunks"][0],
            outs["NumCorrectChunks"][0])


# ---------------------------------------------------------------------------
# Remaining vision layers (ops/vision_extra.py)
# reference: layers/nn.py pool3d, spp (via nets), roi_pool:6690,
# roi_align:6740, affine_channel:9406, affine_grid:7576, crop:5765,
# unpool.
# ---------------------------------------------------------------------------

def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "strides": pool_stride, "paddings": pool_padding,
               "global_pooling": global_pooling, "exclusive": exclusive})
    return out


def spp(input, pyramid_height=3, pool_type="max", name=None):
    helper = LayerHelper("spp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="spp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": int(pyramid_height),
                            "pooling_type": pool_type})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, name=None):
    """rois: (R, 5) [batch_idx, x1, y1, x2, y2]."""
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale)})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_align", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale),
               "sampling_ratio": int(sampling_ratio)})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None):
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]})
    return out


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    ins = {"Theta": [theta]}
    attrs = {}
    if isinstance(out_shape, (list, tuple)):
        attrs["output_shape"] = [int(s) for s in out_shape]
    else:
        ins["OutputShape"] = [out_shape]
    helper.append_op(type="affine_grid", inputs=ins,
                     outputs={"Output": [out]}, attrs=attrs)
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x]}
    attrs = {"offsets": list(offsets or [])}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = [int(s) for s in shape]
    elif shape is not None:
        ins["Y"] = [shape]
    helper.append_op(type="crop", inputs=ins, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def unpool(input, indices, unpool_size, name=None):
    """Max unpooling from pool2d_with_index's Mask."""
    helper = LayerHelper("unpool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="unpool", inputs={"X": [input], "Indices": [indices]},
        outputs={"Out": [out]},
        attrs={"unpool_size": [int(s) for s in unpool_size]})
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both", name=None):
    """In-graph tensor dump (reference layers/control_flow.py Print;
    lowered to jax.debug.print, which streams asynchronously from the
    device)."""
    helper = LayerHelper("print", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or "",
                            "summarize": int(summarize)})
    out.desc.shape = tuple(input.shape)
    return out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Call a python function from inside the compiled program
    (reference layers/nn.py py_func → py_func_op.cc; here a
    jax.pure_callback host round-trip).  `out` is a Variable or list of
    Variables with declared shapes/dtypes.  backward_func is not
    supported: the callback is opaque to jax AD, so use it on
    stop-gradient paths (metrics, logging, data munging)."""
    from ..ops.misc import register_py_func

    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func: the host callback is opaque to jax "
            "AD; compute gradients in-graph instead")
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    handle = register_py_func(func)
    helper.append_op(
        type="py_func", inputs={"X": list(xs)},
        outputs={"Out": list(outs)},
        attrs={"handle": handle,
               "out_shapes": [list(o.shape) for o in outs],
               "out_dtypes": [str(o.dtype) for o in outs]})
    return out


# ---------------------------------------------------------------------------
# Straggler ops (round-3 sweep): mean_iou, similarity_focus, psroi_pool,
# random_crop, conv_shift, modified_huber_loss, positive_negative_pair.
# reference: layers/nn.py mean_iou:6957, similarity_focus:8951,
# psroi_pool:9628, random_crop:6814; conv_shift_op.cc,
# modified_huber_loss_op.cc, positive_negative_pair_op.cc (op-level APIs).
# ---------------------------------------------------------------------------

def mean_iou(input, label, num_classes):
    """Mean IoU over classes (reference layers/nn.py mean_iou:6957).
    Returns (mean_iou scalar, out_wrong (C,), out_correct (C,))."""
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": int(num_classes)})
    return miou, wrong, correct


def similarity_focus(input, axis, indexes, name=None):
    """Similarity-focus mask (reference layers/nn.py
    similarity_focus:8951)."""
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="similarity_focus", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": int(axis), "indexes": [int(i) for i in indexes]})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    """Position-sensitive ROI pooling for R-FCN (reference layers/nn.py
    psroi_pool:9628); rois (R, 5) [batch_idx, x1, y1, x2, y2]."""
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="psroi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"output_channels": int(output_channels),
               "spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width)})
    return out


def random_crop(x, shape, seed=None):
    """Per-instance random crop (reference layers/nn.py random_crop:6814).
    Randomness comes from the program RNG state rather than a threaded
    Seed tensor; `seed` is accepted for API parity and ignored."""
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="random_crop", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": [int(s) for s in shape]})
    return out


def conv_shift(x, y, name=None):
    """Circular convolution (reference conv_shift_op.cc, Neural Turing
    Machine shift weighting): X (B, M), Y (B, N) with N odd -> (B, M)."""
    helper = LayerHelper("conv_shift", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="conv_shift", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def modified_huber_loss(x, y, name=None):
    """Modified Huber loss for binary classification (reference
    modified_huber_loss_op.cc); x = f(x) scores (N, 1), y labels in
    {0, 1} (N, 1)."""
    helper = LayerHelper("modified_huber_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inter = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="modified_huber_loss",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out], "IntermediateVal": [inter]})
    return out


def positive_negative_pair(score, label, query_id, weight=None, column=-1,
                           accumulators=None, name=None):
    """Learning-to-rank pair counts (reference
    positive_negative_pair_op.cc).  Returns (pos, neg, neutral) scalars;
    `accumulators` is an optional (pos, neg, neu) tuple of previous
    totals to stream across batches."""
    helper = LayerHelper("positive_negative_pair", name=name)
    pos = helper.create_variable_for_type_inference("float32")
    neg = helper.create_variable_for_type_inference("float32")
    neu = helper.create_variable_for_type_inference("float32")
    ins = {"Score": [score], "Label": [label], "QueryID": [query_id]}
    if weight is not None:
        ins["Weight"] = [weight]
    if accumulators is not None:
        ins["AccumulatePositivePair"] = [accumulators[0]]
        ins["AccumulateNegativePair"] = [accumulators[1]]
        ins["AccumulateNeutralPair"] = [accumulators[2]]
    helper.append_op(type="positive_negative_pair", inputs=ins,
                     outputs={"PositivePair": [pos], "NegativePair": [neg],
                              "NeutralPair": [neu]},
                     attrs={"column": int(column)})
    return pos, neg, neu


def fused_vocab_softmax_ce(hidden, weight, label, epsilon=0.0,
                           use_pallas=False, block_t=None, block_v=None,
                           name=None):
    """Per-token label-smoothed CE of `hidden @ weight` computed WITHOUT
    materializing the (tokens, vocab) logits (ops/pallas/vocab_ce.py) —
    the fused big-vocab loss for NMT/LM heads.  hidden (..., D), weight
    (D, V) parameter, label int ids with hidden's leading shape.
    block_t/block_v default to the kernel module's VMEM-budgeted
    defaults (ops/pallas/vocab_ce.py DEFAULT_BLOCK_*); override only
    with a measured win."""
    helper = LayerHelper("fused_vocab_softmax_ce", name=name)
    loss = helper.create_variable_for_type_inference("float32")
    attrs = {"epsilon": float(epsilon), "use_pallas": bool(use_pallas)}
    if block_t is not None:
        attrs["block_t"] = int(block_t)
    if block_v is not None:
        attrs["block_v"] = int(block_v)
    helper.append_op(
        type="fused_vocab_softmax_ce",
        inputs={"Hidden": [hidden], "W": [weight], "Label": [label]},
        outputs={"Loss": [loss]},
        attrs=attrs)
    return loss
