"""paddle_tpu.resilience — fault tolerance for training and serving.

The production-scale counterpart to observe/ (which only *sees*
failures): this subsystem survives them (docs/RESILIENCE.md):

- `guard`: in-step non-finite update guard + dynamic loss scaling —
  a NaN step is skipped ON DEVICE inside the one jitted step
  (`enable_update_guard`, or `amp.decorate(...,
  use_dynamic_loss_scaling=True)`),
- checkpoint integrity (io.py): per-shard CRC32 verified on load, a
  structured `CheckpointError` hierarchy (`errors`), and
  contrib.Trainer falling back to the newest *valid* serial,
- `watchdog`: `Deadline` (SIGALRM guard for hung compiles/dispatches),
  `probe_backend` (subprocess init probe), `retry_call` (bounded
  exponential backoff) — shared by bench.py, Trainer, ServingEngine,
- the serving circuit breaker lives with its state machine in
  `paddle_tpu.serving.admission` (DEGRADED state, `CircuitBreaker`),
- `preempt`: preemption tolerance — `SnapshotWriter` (async checkpoint
  writes: blocking device→host snapshot, background CRC+manifest-last
  write, failures surfaced as structured `CheckpointWriteError`s) and
  the SIGTERM/SIGINT drain controller contrib.Trainer uses to finish
  the in-flight step, write an emergency checkpoint, and exit with
  `PREEMPT_EXIT_CODE`,
- `health`: the distributed health plane — per-rank KV-store
  heartbeats, a monitor raising structured `PeerLostError`/
  `PeerStalledError` within a configured miss budget, the gang
  **poison key** every rank (and `io._barrier`) checks so one failure
  becomes a bounded-time gang-wide abort, and per-rank step-rate skew
  telemetry (`gang_skew`/`rank_slow` events),
- `supervisor`: the self-healing gang supervisor — spawns N worker
  processes, translates the exit-code registry (77 preempt-drain /
  43 peer-lost), kills the remainder of a broken gang within a grace
  period, and relaunches with a restart budget + deterministic
  backoff, resuming from the newest valid checkpoint
  (`tools/launch_gang.py` is the CLI),
- `autopilot`: the divergence autopilot — `RecoveryController` drives
  contrib.Trainer through a bounded escalation ladder (absorb via the
  guard → in-process rollback to the newest verified-good checkpoint →
  quarantine of the poisoned data window → structured
  `TrainingDivergedError` halt with a FlightRecorder bundle once the
  rollback budget is spent),
- `chaos`: deterministic fault injectors (failpoints, delaypoints, NaN
  batches, shard corruption, torn checkpoints, executor failure
  bursts, env-armed per-rank kill/hang for gang workers, in-process
  serving-replica kill/delay for fleet failover proofs, `FakeKv`)
  that the tests and the CI chaos smokes use to prove all of the
  above.
"""

from . import autopilot  # noqa: F401
from . import chaos  # noqa: F401
from . import health  # noqa: F401
from . import preempt  # noqa: F401
from . import supervisor  # noqa: F401
from .autopilot import (AutopilotConfig,  # noqa: F401
                        RecoveryController)
from .chaos import (ChaosKilled, FakeKv, FlakyPredictor,  # noqa: F401
                    corrupt_file, corrupt_shard, delay_replica,
                    hang_rank, kill_rank, kill_replica, nan_reader,
                    poison_feed, tear_checkpoint)
from .errors import (CheckpointBarrierPoisonedError,  # noqa: F401
                     CheckpointBarrierTimeoutError,
                     CheckpointCorruptError, CheckpointError,
                     CheckpointFormatError, CheckpointIncompleteError,
                     CheckpointNotFoundError, CheckpointStateMismatchError,
                     CheckpointWriteError, GangError, GangFailedError,
                     GangPoisonedError, PeerLostError, PeerStalledError,
                     ResilienceError, RetriesExhaustedError,
                     StepHangError, TrainingDivergedError,
                     TrainingPreempted, WatchdogTimeout)
from .guard import (LossScaleConfig, UpdateGuardConfig,  # noqa: F401
                    enable_update_guard, guard_config)
from .health import (PEER_LOST_EXIT_CODE, HealthConfig,  # noqa: F401
                     HealthPlane, get_health_plane, poison_gang,
                     start_health_plane, stop_health_plane)
from .preempt import (PREEMPT_EXIT_CODE, PendingSave,  # noqa: F401
                      SnapshotWriter, clear_drain, drain_requested,
                      install_preempt_handler, request_drain,
                      uninstall_preempt_handler)
from .supervisor import (GangResult, Supervisor,  # noqa: F401
                         classify_exit)
from .watchdog import (Deadline, DispatchWatchdog,  # noqa: F401
                       backoff_schedule, probe_backend, retry_call)
