"""paddle_tpu.observe — device-side telemetry for the TPU runtime.

Three pillars (docs/OBSERVE.md):

1. TRACE ATTRIBUTION — the executor wraps every op lowering in
   `jax.named_scope("<op_type>:<op_index>")` so jax.profiler traces and
   XLA HLO metadata carry fluid op names end-to-end; `trace.py` parses
   a captured trace back into the fluid profiler's per-op time table
   (`profiler.profiler(sorted_key=...)` prints it).

2. DEVICE-SIDE METRICS — `StepTelemetry` accumulates loss/grad-norm/
   update-norm/non-finite counters INSIDE the jitted step (extra carry
   state, no host round-trips, no callbacks — the tunnel backend
   forbids them) and is fetched every N steps in one sync; host-side
   `runtime_stats` counts XLA compiles (+wall time, via
   jax.monitoring), executor retraces, and dispatch latency.

3. STRUCTURED RUN EVENTS — `RunEventLog` writes JSONL records with
   run-id/git-sha/backend/mesh provenance, consumed by
   contrib.Trainer(telemetry=...), bench.py, and tools/run_ab.py.

4. COST ATTRIBUTION — `cost.py` walks the *optimized* HLO module with
   the same wire scanner, computing analytic per-instruction flops and
   materialized-buffer bytes, injecting the Pallas kernel cost
   registry at custom calls, and joining to fluid ops + measured
   device time (`op_cost_table`); tools/roofline.py and bench.py's
   Pallas MFU numerators are built on it.

5. MEMORY — `memory.py` parses the optimized module's buffer
   assignment (compiled.memory_analysis()), attributing every HBM
   buffer to its fluid op and classifying it (params / optimizer_state
   / gradients / activations / workspace, donated tallied):
   `memory_report`/`memory_table` + `format_memory_table`, the
   `memory_timeline` live-bytes curve (chrome-trace exportable), and
   `plan_fit` — peak-HBM prediction for a candidate (batch, seq,
   dtype, remat) config from two small probe compiles, without ever
   compiling the candidate.  serving.ServingEngine validates its
   bucket ladder with it; bench.py entries carry `mem_breakdown`.

7. PER-REQUEST TRACING + METRICS EXPORT — `reqtrace.py` threads a
   host-side `RequestTrace` (monotonic spans at queue boundaries
   only: zero device round-trips) through the serving stack —
   admission, batch formation, dispatch, decode joins, preemption,
   failover/hedge hops — with head sampling plus tail-based keep
   (slow/error/failover always survive), a bounded ring, and
   `export_chrome_trace` (rows = replica, so one trace draws across
   replica rows under chaos); `registry.py` is the pull-model
   `MetricsRegistry` joining every subsystem's existing snapshot
   surface (StepTelemetry, RuntimeStats, Serving/Decode/Fleet stats,
   gang heartbeat skew, memory peaks) into `metrics_snapshot()`,
   Prometheus text exposition (LatencyHistogram log bins mapped
   exactly onto cumulative `le` buckets), and an opt-in localhost
   `MetricsServer` (/metrics + /healthz) on Fleet/Trainer.

6. NUMERICS — `numerics.py` (the production replacement for the
   reference's host-side per-op NaN scan, operator.cc:943): per-layer
   training dynamics (grad/param norms + update ratio per NAMED
   parameter group, the sharding-layer names) as vector fields riding
   the `__telemetry__` accumulator, and first-nonfinite op provenance
   — a per-op finite bitmap computed in-step and latched on the first
   poisoned step, joined host-side to the fluid op desc
   (`numerics_report`/`format_numerics_table`;
   `StepTelemetry.groups`/`.first_nonfinite_op`).  All device-side,
   zero extra dispatches, byte-identical step when disabled.

8. GOODPUT — `goodput.py` accounts every second of a training run's
   WALL clock into exclusive categories (step / replay / compile /
   data_stall / checkpoint / recovery / barrier_wait / idle,
   Σ == wall):
   host-monotonic timestamps at phase boundaries only, zero device
   dispatches, byte-identical step lowering.  `GoodputLedger.report`
   yields the goodput fraction and `effective_mfu` = headline MFU x
   goodput; `export_chrome_trace` draws the step-anatomy timeline on
   rows aligned with reqtrace's exporter; `goodput_collector` feeds
   /metrics.  contrib.Trainer threads it (`Trainer.goodput()`).

9. ALERTING + FLIGHT RECORDING — `alerts.py` is the layer that
   *watches* pillars 1-8: declarative rules (threshold, multi-window
   burn-rate, z-score anomaly) evaluated on a background thread over
   `MetricsRegistry` snapshots, each walking a pending→firing→resolved
   state machine with `for_duration`/hysteresis, emitting registered
   `alert_*` events, exporting an `alerts` metric family + `/alerts`
   route, and exposing `signals()` for the future autoscaler;
   `flightrec.py` writes rate-limited, size-bounded diagnostic
   bundles (event tail, metrics snapshot, reqtrace export, goodput
   table, numerics provenance, thread stacks) on firing alerts,
   watchdog hangs, and unhandled crashes.  Pure host, zero device
   dispatches, byte-identical step lowering on vs off.
"""

from . import cost  # noqa: F401
from .alerts import (AlertEngine, AlertRule, AnomalyRule,  # noqa: F401
                     BurnRateRule, MetricSelector, ThresholdRule,
                     disagg_rule_pack, fleet_rule_pack,
                     serving_rule_pack, snapshot_value,
                     speculate_rule_pack, trainer_rule_pack)
from .cost import (bucket_summary, copyish_instructions,  # noqa: F401
                   device_peaks, flash_boundary_layout,
                   format_cost_table, layout_byte_share, op_cost_table,
                   program_costs)
from .events import (ALERT_EVENTS, DECODE_EVENTS,  # noqa: F401
                     DISAGG_EVENTS, FEED_EVENTS, FLEET_EVENTS,
                     FLIGHT_EVENTS, GANG_EVENTS, GOODPUT_EVENTS,
                     NUMERICS_EVENTS, RECOVERY_EVENTS,
                     RESILIENCE_EVENTS, SERVING_EVENTS,
                     SPECULATE_EVENTS, BoundEventLog,
                     RunEventLog, git_sha, new_run_id, read_events,
                     register_event_kinds, set_strict_kinds)
from .flightrec import FlightRecorder  # noqa: F401
from .goodput import (CATEGORIES as GOODPUT_CATEGORIES,  # noqa: F401
                      GoodputLedger, format_goodput_table,
                      goodput_report)
from .memory import (DEVICE_HBM_BYTES, PLAN_FIT_REL_TOL,  # noqa: F401
                     device_memory_budget, export_chrome_trace,
                     format_memory_table, memory_report, memory_table,
                     memory_timeline, plan_fit, resident_state_bytes,
                     sharded_memory_report, step_mem_breakdown)
from .metrics import (TELEMETRY_VAR, StepTelemetry,  # noqa: F401
                      enable_telemetry, fetch_telemetry, init_telemetry,
                      telemetry_enabled)
from .monitoring import (LatencyHistogram, RuntimeStats,  # noqa: F401
                         device_memory_stats, peak_memory_bytes,
                         runtime_stats)
from .numerics import (GROUP_NAMES, enable_numerics,  # noqa: F401
                       format_numerics_table, group_of,
                       join_first_nonfinite, numerics_enabled,
                       numerics_report, param_groups,
                       worst_update_ratio)
from .registry import (MetricFamily, MetricsRegistry,  # noqa: F401
                       MetricsServer, default_registry,
                       disagg_collector, fleet_collector,
                       gang_collector, goodput_collector,
                       memory_collector, metrics_snapshot,
                       process_collector, recovery_collector,
                       runtime_collector,
                       serving_stats_collector, standard_collectors,
                       telemetry_collector, tracer_collector)
from .reqtrace import (TAIL_KEEP_MARKS, ReqTracer,  # noqa: F401
                       RequestTrace, Span, new_trace_id)
from .trace import fluid_op_of, format_op_table, op_time_table  # noqa: F401


class TelemetryConfig:
    """How contrib.Trainer publishes telemetry.

    interval: fetch the device accumulator every N steps (the
        "device-accumulate, periodic-fetch" cadence — never per-step).
    log_path: write telemetry windows to this JSONL file (a
        RunEventLog is created per training run).
    event_log: alternatively, an existing RunEventLog to emit into.
    numerics: also enable observe pillar 6 on the train program —
        per-layer (named parameter group) training dynamics riding the
        same accumulator, and first-nonfinite op provenance; a window
        that latched a poisoned step additionally emits a
        `nonfinite_provenance` event through the RunEventLog.
    max_log_bytes: size-bound the JSONL log created from `log_path`
        (RunEventLog max_bytes rotation); None = unbounded.
    """

    def __init__(self, interval: int = 10, log_path=None, event_log=None,
                 numerics: bool = False, max_log_bytes=None):
        if interval < 1:
            raise ValueError("telemetry interval must be >= 1")
        self.interval = int(interval)
        self.log_path = log_path
        self.event_log = event_log
        self.numerics = bool(numerics)
        self.max_log_bytes = max_log_bytes
