"""High-level Trainer / Inferencer with checkpoint-based recovery.

TPU-native analog of the reference contrib trainer
(reference: python/paddle/fluid/contrib/trainer.py — Trainer:100 event
loop over epochs with BeginEpoch/BeginStep/EndStep/EndEpoch events,
CheckpointConfig:100 epoch/step cadence, _save_checkpoint/
_load_checkpoint recovery at :580/:1047; Inferencer).

This is also the framework's failure-recovery story (SURVEY.md §5.3):
synchronous ICI training has no per-worker elasticity, so recovery =
periodic checkpoints + restart-and-resume.  Trainer checkpoints
persistables plus its own (epoch, step) cursor at the configured
cadence, and a restarted Trainer resumes from the newest valid
checkpoint automatically — the TPU equivalent of the reference's
trainer-0 persistables + checkpoint_notify flow.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import io as fluid_io
from ..core.executor import Executor, Scope, scope_guard
from ..core.program import Program, default_main_program, program_guard


# versioned schema of the `train_state` payload inside
# __trainer_state__.json (docs/RESILIENCE.md, exact-resume section).
# v1: rng_key, telemetry (loss-scale/guard counters), data_cursor,
# unique_name_ids, optional reader_state.  A NEWER version on disk is
# rejected loudly (CheckpointFormatError); older/absent payloads load
# with whatever they carry (pre-v1 checkpoints resume params+cursor
# only, as before).
TRAIN_STATE_VERSION = 1


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class _RollbackSignal(Exception):
    """Internal control flow: unwind the epoch loop to the restored
    cursor after an autopilot rollback (never escapes train())."""

    def __init__(self, epoch: int, step: int):
        super().__init__(f"rollback to epoch {epoch} step {step}")
        self.epoch = epoch
        self.step = step


class CheckpointConfig:
    """reference contrib/trainer.py CheckpointConfig:100.

    async_save: take only the device→host snapshot on the training
    thread and run the serialization/manifest phase on a background
    SnapshotWriter (resilience.preempt) — a save then stalls the step
    loop for `snapshot_ms`, not the full write time.  Write failures
    surface as structured CheckpointWriteErrors on the next save or at
    train end, never silently (docs/RESILIENCE.md)."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3,
                 epoch_interval: int = 1, step_interval: int = 10,
                 async_save: bool = False):
        self.checkpoint_dir = checkpoint_dir or "checkpoints"
        self.max_num_checkpoints = max(1, int(max_num_checkpoints))
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        self.async_save = bool(async_save)


class Trainer:
    """Event-driven training loop with checkpoint/resume.

        def train_func():
            loss = build_network()
            return loss                      # or [loss, metric, ...]

        trainer = Trainer(train_func=train_func,
                          optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                          checkpoint_config=CheckpointConfig("ckpts"))
        trainer.train(num_epochs=3, event_handler=handler,
                      reader=batch_dict_reader, feed_order=[...])
    """

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 place=None, checkpoint_config: Optional[CheckpointConfig]
                 = None, scope: Optional[Scope] = None, telemetry=None,
                 step_deadline_s: Optional[float] = None,
                 preempt_drain: bool = False, mesh=None,
                 build_strategy=None, autopilot=None,
                 validate_feed: bool = False):
        """telemetry: an observe.TelemetryConfig — enables the
        device-side StepTelemetry accumulator on the train program and
        publishes a window (telemetry means + compile/retrace/dispatch
        runtime stats) every `interval` steps, to the configured JSONL
        event log when one is given.  The accumulator lives inside the
        jitted step; the only added host traffic is ONE fetch per
        window (never per-step — CLAUDE.md tunnel-backend rule).

        step_deadline_s: wall-clock watchdog around each training step
        (resilience.DispatchWatchdog) — a hung dispatch raises a
        structured StepHangError instead of stalling forever, after
        emitting a `step_hang` event and poisoning the gang when the
        health plane is active.  The FIRST step (no completed dispatch
        yet — XLA legitimately compiles for minutes) gets the longer
        compile-grace budget; steady-state steps get step_deadline_s
        (a previously-working step that stops returning is the
        hung-collective signature).

        preempt_drain: install the SIGTERM/SIGINT drain handler at
        train() start (resilience.preempt; main-thread-only, degrades
        to a no-op elsewhere).  On a signal the in-flight step
        finishes, any in-flight async save is awaited, an EMERGENCY
        checkpoint is written, `preempt_drain`/`ckpt_emergency` events
        are emitted, and train() raises TrainingPreempted carrying
        PREEMPT_EXIT_CODE.  The drain-flag check itself always runs —
        tests (and embedders with their own signal plumbing) can call
        resilience.preempt.request_drain() directly.

        mesh: a jax mesh (parallel.make_mesh) — the train program is
        compiled data-parallel over it (CompiledProgram
        .with_data_parallel; feeds shard over the batch axis, params
        follow build_strategy).  build_strategy: a parallel
        BuildStrategy — its `grad_sync` knob ("bf16"/"int8"/
        GradSyncConfig) opts gradient exchange into the explicit
        (optionally blockwise-int8-quantized) all-reduce instead of
        the implicit GSPMD one (docs/DIST.md).

        autopilot: a resilience.AutopilotConfig (or True for
        defaults) — the divergence autopilot (docs/RESILIENCE.md
        §autopilot): on a guard-skip streak or loss/grad-norm z-trip
        the trainer rolls back IN PROCESS to the newest verified-good
        checkpoint, quarantines the poisoned data window on replay,
        and — once the rollback budget is spent — halts with a
        structured TrainingDivergedError plus a flight-recorder
        bundle.  Requires telemetry= (the trigger signals ride the
        telemetry windows) and checkpoint_config= (rollback needs
        serials); the update guard (resilience.enable_update_guard)
        supplies the skip-streak signal.  Pure host: the step
        lowering is byte-identical with the autopilot on or off.

        validate_feed: host-side admission check on every batch
        (data.pipeline.validate_feed_batch) BEFORE it reaches the
        device — a non-finite or signature-drifted batch is dropped
        with a `feed_quarantined` event + counter (feed_stats), and
        counted into the autopilot's quarantine ledger when one is
        attached."""
        self.checkpoint_cfg = checkpoint_config
        self.telemetry_cfg = telemetry
        self.step_deadline_s = step_deadline_s
        self.preempt_drain = bool(preempt_drain)
        self.scope = scope or Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.place = place
        # fresh unique_name counters so generated var names (optimizer
        # lr/accumulators, tmp params) are deterministic across process
        # restarts — required for checkpoint resume (fluid's Trainer
        # builds under unique_name.guard for the same reason)
        from ..core import unique_name

        with unique_name.guard(), \
                program_guard(self.train_program, self.startup_program):
            outs = train_func()
            if isinstance(outs, (list, tuple)):
                self.train_outputs = list(outs)
            else:
                self.train_outputs = [outs]
            optimizer = optimizer_func()
            optimizer.minimize(self.train_outputs[0])
            # generated-name counters at the end of the build: saved in
            # every checkpoint's train_state and compared at resume — a
            # build whose counters drifted (e.g. run outside
            # unique_name.guard()) would silently bind saved arrays to
            # the wrong variables; the comparison makes it loud
            self._uname_ids = dict(unique_name.generator.ids)
        self.mesh = mesh
        if mesh is not None:
            # multi-device training: wrap the built program so every
            # exe.run routes through the sharded step (Executor.run
            # consults _compiled_wrapper); checkpoint resume already
            # reads the wrapper's mesh for load_sharded below
            from ..parallel.compiler import CompiledProgram

            CompiledProgram(self.train_program).with_data_parallel(
                loss_name=self.train_outputs[0].name,
                build_strategy=build_strategy, mesh=mesh)
        self._ckpt_writer = None       # lazy SnapshotWriter (async_save)
        self._pending_save = None      # in-flight resilience.PendingSave
        self._step_watchdog = None     # DispatchWatchdog (step_deadline_s)
        self._gang_steps = 0           # heartbeat step counter (beat())
        self._active_reader = None
        self._resume_reader_state = None
        # observe pillar 8: every second of train() wall clock lands in
        # exactly one ledger category (step/replay/compile/data_stall/
        # checkpoint/recovery/barrier_wait/idle) — pure host
        # bookkeeping, the traced step is byte-identical with or
        # without it
        from ..observe.goodput import GoodputLedger

        self.goodput_ledger = GoodputLedger()
        # blocking_ms/write_ms are READS of the goodput ledger's
        # checkpoint category / ckpt_write background channel — one
        # source for the same milliseconds across train_end, bench and
        # /metrics (the keys survive as aliases for perf_gate baselines)
        self.ckpt_stats = {"saves": 0, "blocking_ms": 0.0,
                           "write_ms": 0.0, "bytes": 0}
        self.validate_feed = bool(validate_feed)
        self.feed_stats = {"quarantined": 0}
        self._feed_signature = None
        self.autopilot = None
        self._window_dirty = False   # last published window poisoned?
        self._in_recovery = False    # between rollback and re-entry
        if autopilot:
            from ..resilience.autopilot import (AutopilotConfig,
                                                RecoveryController)

            if telemetry is None:
                raise ValueError(
                    "autopilot= requires telemetry= — the recovery "
                    "controller consumes the periodic telemetry "
                    "windows (observe.TelemetryConfig)")
            if checkpoint_config is None:
                raise ValueError(
                    "autopilot= requires checkpoint_config= — "
                    "rollback needs verified-good serials to restore")
            cfg = (autopilot if isinstance(autopilot, AutopilotConfig)
                   else AutopilotConfig())
            self.autopilot = RecoveryController(cfg)
        self.last_telemetry = None     # newest StepTelemetry window
        #                                (the metrics-registry source)
        self._metrics_registry = None
        self._metrics_server = None
        self.alert_engine = None       # observe pillar 9 (opt-in)
        self.flight_recorder = None
        self._event_log = None
        if self.telemetry_cfg is not None:
            from .. import observe

            observe.enable_telemetry(self.train_program)
            if getattr(self.telemetry_cfg, "numerics", False):
                # observe pillar 6: per-group dynamics + first-
                # nonfinite provenance ride the same accumulator; a
                # poisoned window additionally emits a
                # `nonfinite_provenance` event below
                observe.enable_numerics(self.train_program)
            self._event_log = self.telemetry_cfg.event_log
            if self._event_log is None and self.telemetry_cfg.log_path:
                self._event_log = observe.RunEventLog(
                    self.telemetry_cfg.log_path,
                    meta={"source": "contrib.Trainer"},
                    max_bytes=getattr(self.telemetry_cfg,
                                      "max_log_bytes", None))
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
        # resume point restored from the newest checkpoint: the epoch to
        # continue in, plus how many of its batches were already consumed
        self._resume_epoch = 0
        self._resume_step_in_epoch = 0
        if self.checkpoint_cfg:
            self._try_resume()

    # -- checkpointing ---------------------------------------------------
    def _ckpt_root(self) -> str:
        return self.checkpoint_cfg.checkpoint_dir

    def _list_checkpoints(self) -> List[int]:
        root = self._ckpt_root()
        if not os.path.isdir(root):
            return []
        ids = []
        for d in os.listdir(root):
            if d.startswith("ckpt_") and os.path.exists(
                    os.path.join(root, d, "__trainer_state__.json")):
                try:
                    ids.append(int(d.split("_")[1]))
                except ValueError:
                    continue
        return sorted(ids)

    def _emit(self, kind: str, **fields):
        """Checkpoint/resume lifecycle events go to the event log when
        one is configured AND to stderr — a resume that silently
        skipped a corrupt checkpoint is an incident nobody can debug."""
        import sys

        if self._event_log:
            self._event_log.event(kind, **fields)
        print(f"Trainer {kind}: "
              + " ".join(f"{k}={v}" for k, v in fields.items()),
              file=sys.stderr)

    # -- full-state capture (bit-exact resume; docs/RESILIENCE.md) ------
    def _capture_train_state(self, epoch: int, step: int) -> dict:
        """Everything a bit-exact resume needs BEYOND the persistable
        arrays: the RNG stream (dropout), the telemetry accumulator
        (dynamic loss-scale value + good/bad counters, guard skip
        counter), the data cursor, optional reader state, and the
        generated-name counters of the build (drift detector)."""
        from ..core.executor import RNG_STATE_VAR
        from ..observe.metrics import TELEMETRY_VAR

        st = {
            "version": TRAIN_STATE_VERSION,
            "data_cursor": {"epoch": epoch, "step_in_epoch": step},
            "unique_name_ids": dict(self._uname_ids),
        }
        rng = self.scope.find_var(RNG_STATE_VAR)
        if rng is not None:
            arr = np.asarray(rng)
            st["rng_key"] = {"dtype": str(arr.dtype),
                             "data": arr.tolist()}
        tel = self.scope.find_var(TELEMETRY_VAR)
        if tel is not None:
            # numerics vector fields (per-group norms, the latched
            # bitmap) serialize as lists; scalars stay scalars
            st["telemetry"] = {
                k: (np.asarray(v).item() if np.asarray(v).ndim == 0
                    else np.asarray(v).tolist())
                for k, v in tel.items()}
        reader = self._active_reader
        if reader is not None and hasattr(reader, "state_dict"):
            st["reader_state"] = reader.state_dict()
        return st

    def _validate_train_state(self, st: dict) -> None:
        """Version + build-identity gate, BEFORE any array loads."""
        from ..resilience.errors import (CheckpointFormatError,
                                         CheckpointStateMismatchError)

        version = int(st.get("version", 0))
        if version > TRAIN_STATE_VERSION:
            raise CheckpointFormatError(
                f"checkpoint train_state version {version} is newer "
                f"than this build reads (<= {TRAIN_STATE_VERSION})",
                version=version, supported=TRAIN_STATE_VERSION)
        saved = st.get("unique_name_ids")
        if saved is not None and dict(saved) != dict(self._uname_ids):
            drift = sorted(
                k for k in set(saved) | set(self._uname_ids)
                if saved.get(k) != self._uname_ids.get(k))
            raise CheckpointStateMismatchError(
                "generated-name counters of this build do not match "
                "the checkpoint's — the training program was built "
                "with different unique_name state (was it built "
                "outside unique_name.guard()?).  Loading would bind "
                f"saved arrays to the wrong variables.  Drifted keys: "
                f"{drift[:8]}", drifted_keys=drift[:32],
                saved_count=len(saved), built_count=len(self._uname_ids))

    def _restore_train_state(self, st: dict) -> None:
        """Write the captured non-array state back into the scope (the
        arrays were already loaded)."""
        import jax.numpy as jnp

        from ..core.executor import RNG_STATE_VAR
        from ..observe.metrics import TELEMETRY_VAR, init_telemetry_for

        rng = st.get("rng_key")
        if rng is not None:
            self.scope.set_var(
                RNG_STATE_VAR,
                jnp.asarray(np.array(rng["data"],
                                     dtype=np.dtype(rng["dtype"]))))
        tel = st.get("telemetry")
        if tel is not None:
            # dtype/shape template per field (init_telemetry_for sizes
            # the numerics vectors for THIS build's program); fields
            # the checkpoint lacks — or whose shape drifted with the
            # program — stay zeroed
            fresh = init_telemetry_for(self.train_program)
            for k, v in tel.items():
                if k not in fresh:
                    continue
                tmpl = np.asarray(fresh[k])
                if tmpl.ndim == 0:
                    fresh[k] = tmpl.dtype.type(v)
                else:
                    arr = np.asarray(v, dtype=tmpl.dtype)
                    if arr.shape == tmpl.shape:
                        fresh[k] = arr
            self.scope.set_var(TELEMETRY_VAR, fresh)
        self._resume_reader_state = st.get("reader_state")

    # -- save ------------------------------------------------------------
    def _save_checkpoint(self, serial: int, epoch: int, step: int,
                         emergency: bool = False,
                         force_sync: bool = False):
        root = self._ckpt_root()
        path = os.path.join(root, f"ckpt_{serial}")
        led = self.goodput_ledger
        use_async = (self.checkpoint_cfg.async_save and not force_sync)
        # the whole blocking portion of a save — snapshot, any
        # wait-for-previous, and (sync path) the write itself — is one
        # ledger "checkpoint" phase; blocking_ms below READS it back
        with led.phase("checkpoint", label=f"save:{serial}"):
            if use_async:
                # surface a PREVIOUS background write's failure before
                # starting a new save (async errors are deferred, not
                # lost)
                self._writer().check()
                # bounded queue: a save requested while one is in
                # flight waits for it — two saves never interleave
                # their files
                self._await_pending(surface=True)
            if os.path.isdir(path) and not os.path.exists(
                    os.path.join(path, "__trainer_state__.json")):
                # leftover of a save that died mid-write (torn): clear
                # it so stale shard files cannot mix with the fresh save
                shutil.rmtree(path, ignore_errors=True)
            os.makedirs(path, exist_ok=True)
            # verified-good marking (autopilot anchor + _rotate pin):
            # computed on the training thread at snapshot time, so the
            # verdict describes exactly the state being saved
            verified = self._checkpoint_verified()
            trainer_state = {"epoch": epoch, "step": step,
                             "serial": serial,
                             "verified_good": verified,
                             "train_state":
                             self._capture_train_state(epoch, step)}
            with scope_guard(self.scope):
                # sharded snapshot: each process copies only its own
                # array shards device→host (io.py) — scales to mp/fsdp
                # state that must never gather to one host
                job = fluid_io.prepare_sharded_save(
                    self.exe, path, main_program=self.train_program)

            def _finalize():
                # ordering: shards → manifest (io.py, written LAST
                # there) → trainer state.  The trainer-state file marks
                # the serial visible to _list_checkpoints, so a death
                # anywhere earlier leaves a torn — never a
                # half-resumable — directory.
                tmp = os.path.join(path, "__trainer_state__.json.tmp")
                with open(tmp, "w") as f:
                    json.dump(trainer_state, f)
                os.replace(tmp,
                           os.path.join(path, "__trainer_state__.json"))
                if self.autopilot is not None:
                    # the serial becomes a rollback anchor only after
                    # its state file landed — never before
                    self.autopilot.note_checkpoint(serial, epoch, step,
                                                   verified)
                self._rotate()
                led.note_background("ckpt_write",
                                    (job.write_ms or 0.0) / 1000.0)
                self.ckpt_stats["saves"] += 1
                self.ckpt_stats["write_ms"] = round(
                    led.background_ms("ckpt_write"), 3)
                self.ckpt_stats["bytes"] = job.bytes_total
                self._emit("ckpt_save", serial=serial, epoch=epoch,
                           step=step,
                           snapshot_ms=round(job.snapshot_ms, 3),
                           write_ms=round(job.write_ms or 0.0, 3),
                           bytes=job.bytes_total, asynchronous=use_async,
                           emergency=emergency)

            if use_async:
                self._pending_save = self._writer().submit(
                    job, finalize=_finalize)
            else:
                job.write()
                _finalize()
        # blocking cost = everything inside the phase above, i.e.
        # exactly the time the step loop lost to saves so far
        self.ckpt_stats["blocking_ms"] = round(
            led.category_ms("checkpoint"), 3)

    def _writer(self):
        if self._ckpt_writer is None:
            from ..resilience.preempt import SnapshotWriter

            self._ckpt_writer = SnapshotWriter()
        return self._ckpt_writer

    def _await_pending(self, surface: bool, timeout: float = 600.0):
        """Wait out an in-flight async save.  surface=True re-raises a
        write failure (the per-save contract); surface=False logs it
        as a loud ckpt_async_error and continues — the drain path must
        still write its emergency checkpoint after a failed save."""
        pending, self._pending_save = self._pending_save, None
        if pending is None and self._ckpt_writer is None:
            return
        from ..resilience.errors import CheckpointError

        try:
            if pending is not None:
                pending.result(timeout)
            if self._ckpt_writer is not None:
                self._ckpt_writer.wait_idle(timeout)
        except (CheckpointError, TimeoutError) as e:
            fields = (e.as_dict() if isinstance(e, CheckpointError)
                      else {"error": "timeout", "message": str(e)})
            self._emit("ckpt_async_error", error=fields)
            if surface:
                raise

    def _checkpoint_verified(self) -> bool:
        """The verified-good verdict for the state being saved RIGHT
        NOW: the trailing telemetry window is clean.  Three gates —
        the device accumulator's current (since-last-fetch) nonfinite/
        skip counters are zero, the last PUBLISHED window was clean
        (the accumulator resets at each fetch, so a poison just before
        a fetch would otherwise be invisible at save time), and the
        autopilot (when attached) holds no unresolved anomaly.  A
        trainer without telemetry marks every save verified — it has
        no evidence of poison, and the pre-autopilot rotation
        semantics are unchanged."""
        from ..observe.metrics import TELEMETRY_VAR

        if self._window_dirty:
            return False
        if self.autopilot is not None and not self.autopilot.healthy:
            return False
        tel = self.scope.find_var(TELEMETRY_VAR)
        if tel is not None:
            for k in ("nonfinite_grad_steps", "nonfinite_loss_steps",
                      "skipped_update_steps"):
                v = tel.get(k) if hasattr(tel, "get") else None
                if v is not None and float(np.asarray(v)) > 0:
                    return False
        return True

    def _serial_verified(self, serial: int) -> bool:
        """Read a serial's on-disk verified-good marking (False for
        pre-marking checkpoints and unreadable state files)."""
        path = os.path.join(self._ckpt_root(), f"ckpt_{serial}",
                            "__trainer_state__.json")
        try:
            with open(path) as f:
                return bool(json.load(f).get("verified_good"))
        except (OSError, ValueError):
            return False

    def _rotate(self):
        # rotate (reference keeps max_num_checkpoints, deleting
        # oldest) — EXCEPT the newest verified-good serial, which is
        # pinned: blind oldest-first deletion could evict the last
        # known-good checkpoint while keeping N newer poisoned ones,
        # leaving the autopilot (and crash resume) nothing sane to
        # restore (tests/test_autopilot.py pins the regression)
        root = self._ckpt_root()
        ids = self._list_checkpoints()
        verified = [s for s in ids if self._serial_verified(s)]
        pinned = verified[-1] if verified else None
        victims = [s for s in ids if s != pinned]
        while len(ids) > self.checkpoint_cfg.max_num_checkpoints \
                and victims:
            victim = victims.pop(0)
            ids.remove(victim)
            shutil.rmtree(os.path.join(root, f"ckpt_{victim}"),
                          ignore_errors=True)

    def _load_checkpoint(self, path: str) -> dict:
        """Load one checkpoint dir (trainer cursor + train_state +
        arrays) or raise a structured CheckpointError
        (resilience/errors.py).  The trainer state is read and
        validated FIRST: a version/name-drift mismatch fails loudly
        before any array touches the scope."""
        st = self._read_trainer_state(path)
        train_state = st.get("train_state") or {}
        self._validate_train_state(train_state)
        with scope_guard(self.scope):
            if os.path.exists(os.path.join(path,
                                           fluid_io.SHARD_MANIFEST)):
                # load each var straight into its target sharding when
                # the program was compiled over a mesh (no host gather)
                wrapper = getattr(self.train_program,
                                  "_compiled_wrapper", None)
                mesh = wrapper._mesh if wrapper is not None else None
                fluid_io.load_sharded(self.exe, path,
                                      main_program=self.train_program,
                                      mesh=mesh)
            else:
                # checkpoint from the pre-sharded combined format
                fluid_io.load_persistables(self.exe, path,
                                           main_program=self.train_program)
        self._restore_train_state(train_state)
        return st

    def _read_trainer_state(self, path: str) -> dict:
        from ..resilience.errors import (CheckpointCorruptError,
                                         CheckpointNotFoundError)

        state_path = os.path.join(path, "__trainer_state__.json")
        try:
            with open(state_path) as f:
                return json.load(f)
        except FileNotFoundError as e:
            raise CheckpointNotFoundError(
                f"checkpoint {path!r} has no trainer state (torn save)",
                dirname=path) from e
        except (json.JSONDecodeError, OSError) as e:
            raise CheckpointCorruptError(
                f"unreadable trainer state {state_path!r}: {e}",
                dirname=path, cause=f"{type(e).__name__}: {e}") from e

    def _try_resume(self):
        """Resume from the NEWEST VALID checkpoint: serials are tried
        newest-first, and a torn/corrupt/incomplete one is skipped with
        a loud `ckpt_fallback` record — never a raw numpy/JSON error,
        never a silent fresh start when an older valid serial exists."""
        from ..resilience.errors import (CheckpointError,
                                         CheckpointStateMismatchError)

        ids = self._list_checkpoints()
        for serial in reversed(ids):
            path = os.path.join(self._ckpt_root(), f"ckpt_{serial}")
            try:
                st = self._load_checkpoint(path)
            except CheckpointStateMismatchError:
                # NOT a fallback case: every serial was written by the
                # same (drifted-relative-to-us) build — walking to an
                # older one would mis-bind identically.  Fail loudly.
                raise
            except CheckpointError as e:
                self._emit("ckpt_fallback", serial=serial,
                           error=e.as_dict())
                continue
            self._resume_epoch = int(st.get("epoch", 0))
            self._resume_step_in_epoch = int(st.get("step", 0))
            if serial != ids[-1] or self._event_log:
                self._emit("ckpt_resume", serial=serial,
                           epoch=self._resume_epoch,
                           step=self._resume_step_in_epoch,
                           fallback=serial != ids[-1])
            return
        if ids:
            self._emit("ckpt_resume_failed", tried=list(reversed(ids)))

    # -- the loop --------------------------------------------------------
    def train(self, num_epochs: int, event_handler: Optional[Callable]
              = None, reader: Optional[Callable] = None,
              feed_order: Optional[Sequence[str]] = None):
        """reader: callable -> iterable of feed dicts (or tuples aligned
        with feed_order).  Bit-exact resume additionally requires the
        reader to be DETERMINISTIC (same stream every run — e.g.
        data.decorator.shuffle(seed=...)); a reader exposing
        state_dict()/load_state_dict() gets its state checkpointed and
        restored too."""
        # pillar 8: the ledger window bounds this call's wall clock —
        # every second in here lands in exactly one goodput category
        self.goodput_ledger.open_window()
        try:
            return self._train_impl(num_epochs, event_handler, reader,
                                    feed_order)
        finally:
            self.goodput_ledger.close_window()

    def _train_impl(self, num_epochs: int,
                    event_handler: Optional[Callable],
                    reader: Optional[Callable],
                    feed_order: Optional[Sequence[str]]):
        from ..resilience import health as gang_health
        from ..resilience import preempt

        handler = event_handler or (lambda e: None)
        if self.preempt_drain:
            preempt.install_preempt_handler()
        # gang fault tolerance: when init_distributed registered the
        # health plane, every rank bumps its heartbeat step counter and
        # consults the LOCAL alarm/poison cache between steps (the
        # monitor thread does the KV RPCs — nothing here touches the
        # jitted step or adds per-step host round-trips)
        plane = gang_health.get_health_plane()
        if plane is not None:
            if self._event_log:
                plane.attach_event_log(self._event_log)
            # gang waits outside train() (wait_gang_done) keep feeding
            # the same ledger so the done-rendezvous shows up as
            # barrier_wait, not as unaccounted time
            plane.attach_ledger(self.goodput_ledger)
            plane.check()  # a poisoned gang must not start stepping
        if self.step_deadline_s and self._step_watchdog is None:
            from ..resilience.watchdog import DispatchWatchdog

            def _on_hang(fields):
                # a hang detected HERE is gang-fatal: poison so peers
                # abort their barriers/steps instead of waiting out
                # their own timeouts on this wedged rank
                if plane is not None:
                    plane.poison(
                        f"step hang on rank {plane.rank}: "
                        f"{fields.get('what')}", kind="step_hang",
                        hang=fields)

            on_hang = _on_hang
            if self.flight_recorder is not None:
                # capture the diagnostic bundle BEFORE the gang
                # poison: the abort path may end the process
                on_hang = self.flight_recorder.watchdog_hook(_on_hang)
            self._step_watchdog = DispatchWatchdog(
                self.step_deadline_s, event_log=self._event_log,
                on_hang=on_hang)
        if self.flight_recorder is not None:
            self.flight_recorder.watchdog = self._step_watchdog
        self._active_reader = reader
        if (self._resume_reader_state is not None and reader is not None
                and hasattr(reader, "load_state_dict")):
            reader.load_state_dict(self._resume_reader_state)
        serial = ((self._list_checkpoints() or [-1])[-1] + 1
                  if self.checkpoint_cfg else 0)
        fetch = [o.name for o in self.train_outputs]
        skip = self._resume_step_in_epoch  # mid-epoch fast-forward
        # restart-replay badput: the per-step progress cursor the DEAD
        # process left behind marks how far it actually got; every step
        # we execute before that point is work done twice (the resume
        # checkpoint is older than the crash), accounted as "replay"
        crash_cursor = self._read_progress()
        if (crash_cursor is not None
                and crash_cursor > (self._resume_epoch,
                                    self._resume_step_in_epoch)):
            self.goodput_ledger.note_replay(
                (self._resume_epoch, self._resume_step_in_epoch),
                crash_cursor)
        else:
            crash_cursor = None
        tel_snap = None
        if self.telemetry_cfg is not None:
            from ..observe import runtime_stats

            tel_snap = runtime_stats.snapshot()
            if self._event_log:
                self._event_log.event(
                    "train_begin", num_epochs=num_epochs,
                    resume_epoch=self._resume_epoch,
                    resume_step=self._resume_step_in_epoch)
        epoch = self._resume_epoch
        while epoch < num_epochs:
          try:  # noqa: E111 — rollback unwind point for the whole epoch
            handler(BeginEpochEvent(epoch))
            step = 0
            done = 0
            for batch in self._goodput_batches(
                    iter(reader()) if reader else iter(())):
                # resume semantics: a mid-epoch checkpoint records how
                # many batches of its epoch were consumed; with a
                # deterministic reader, skipping them continues exactly
                # where the dead process stopped (already-trained
                # batches are not replayed onto updated params)
                if skip > 0:
                    skip -= 1
                    step += 1
                    continue
                if self._quarantined(epoch, step):
                    # autopilot rung 3: a batch inside a quarantined
                    # window is consumed (cursor parity with the run
                    # that trained on it) but never trained — the
                    # poison does not get a second chance
                    with self.goodput_ledger.phase(
                            "recovery", label="quarantine"):
                        step += 1
                        self.autopilot.quarantined_batches += 1
                    continue
                if self._in_recovery:
                    # first live batch past the quarantine: caught up —
                    # reader waits are data_stall again, not recovery
                    self._in_recovery = False
                if not isinstance(batch, dict):
                    if feed_order is None:
                        raise ValueError(
                            "tuple batches need feed_order")
                    batch = dict(zip(feed_order, batch))
                if self.validate_feed and self._reject_feed(
                        batch, epoch, step):
                    step += 1
                    continue
                begin = BeginStepEvent(epoch, step)
                handler(begin)
                if self._step_watchdog is not None:
                    guard = self._step_watchdog.guard(
                        what=f"train step {epoch}/{step}")
                else:
                    import contextlib

                    guard = contextlib.nullcontext()
                is_replay = (crash_cursor is not None
                             and (epoch, step) < crash_cursor)
                with scope_guard(self.scope), guard, \
                        self.goodput_ledger.phase(
                            "replay" if is_replay else "step", steps=1):
                    metrics = self.exe.run(
                        self.train_program, feed=batch,
                        fetch_list=fetch if begin.fetch_metrics else [])
                handler(EndStepEvent(epoch, step, metrics))
                step += 1
                done += 1
                if self.checkpoint_cfg:
                    self._write_progress(epoch, step)
                if plane is not None:
                    self._gang_steps += 1
                    with self.goodput_ledger.phase("barrier_wait"):
                        plane.beat(self._gang_steps)
                        plane.check()  # raises PeerLost/Stalled/Poisoned
                if (self.telemetry_cfg is not None and
                        done % self.telemetry_cfg.interval == 0):
                    tel_snap = self._publish_telemetry(epoch, step,
                                                       tel_snap)
                    if self.autopilot is not None:
                        # may raise _RollbackSignal (rung 2) or
                        # TrainingDivergedError (rung 4)
                        self._autopilot_check(epoch, step)
                if (self.checkpoint_cfg and
                        done % self.checkpoint_cfg.step_interval == 0):
                    self._save_checkpoint(serial, epoch, step)
                    serial += 1
                    if self._event_log:
                        self._event_log.event("checkpoint",
                                              serial=serial - 1,
                                              epoch=epoch, step=step)
                if preempt.drain_requested():
                    # the in-flight step already finished (we are at a
                    # step boundary); checkpoint and get out
                    self._drain(serial, epoch, step)
            if skip > 0:
                raise RuntimeError(
                    f"resume cursor expected at least {skip} more batches "
                    f"in epoch {epoch} than the reader produced — the "
                    f"dataset/reader changed since the checkpoint")
            skip = 0  # fast-forward applies to the resume epoch only
            if (self.checkpoint_cfg and
                    (epoch + 1) % self.checkpoint_cfg.epoch_interval == 0):
                self._save_checkpoint(serial, epoch + 1, 0)
                serial += 1
            handler(EndEpochEvent(epoch))
            if preempt.drain_requested():
                self._drain(serial, epoch + 1, 0)
          except _RollbackSignal as rb:  # noqa: E111
            # autopilot rung 2 landed: the scope now holds the
            # verified-good checkpoint — restart its epoch with the
            # fast-forward cursor (skip replays nothing: batches before
            # rb.step were trained pre-rollback and are skipped;
            # batches in [rb.step, fail) hit the quarantine check)
            epoch = rb.epoch
            skip = rb.step
            if (self._resume_reader_state is not None
                    and reader is not None
                    and hasattr(reader, "load_state_dict")):
                reader.load_state_dict(self._resume_reader_state)
            continue
          epoch += 1  # noqa: E111
        # a background write still in flight must land (and a failed
        # one must surface) before train() returns green
        self._await_pending(surface=True)
        if self.telemetry_cfg is not None:
            # flush the partial final window so no steps go unreported
            self._publish_telemetry(num_epochs - 1, -1, tel_snap)
            if self._event_log:
                rep = self.goodput()
                self._event_log.event(
                    "train_end", num_epochs=num_epochs,
                    ckpt_saves=self.ckpt_stats["saves"],
                    # the async win, recorded: how long the step loop
                    # actually stalled vs how long writes took — both
                    # are reads of the goodput ledger now
                    ckpt_blocking_ms=round(
                        self.ckpt_stats["blocking_ms"], 3),
                    ckpt_write_ms=round(
                        self.ckpt_stats["write_ms"], 3),
                    goodput=rep["goodput"],
                    replay_steps=rep["replay_steps"],
                    wall_s=rep["wall_s"])
                self._event_log.event("goodput_report", **rep)

    def _goodput_batches(self, it):
        """Wrap reader `next()` in the ledger's data_stall phase — the
        input pipeline's blocking time, attributed without touching the
        reader or the step.  While replaying past a rollback the same
        waits are autopilot fallout, not pipeline slowness, and land in
        the `recovery` category instead."""
        led = self.goodput_ledger
        while True:
            with led.phase("recovery" if self._in_recovery
                           else "data_stall"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    # -- divergence autopilot (resilience/autopilot.py) ------------------
    def _quarantined(self, epoch: int, pos: int) -> bool:
        """Is reader position (epoch, pos) inside a quarantined data
        window?  Windows are half-open [(e_r, s_r), (e_f, s_f)) in
        tuple order — the batches the diverged timeline consumed after
        the rollback anchor and before detection."""
        if self.autopilot is None:
            return False
        for w in self.autopilot.quarantine_windows:
            if ((w["from_epoch"], w["from_step"]) <= (epoch, pos)
                    < (w["to_epoch"], w["to_step"])):
                return True
        return False

    def _reject_feed(self, batch: dict, epoch: int, step: int) -> bool:
        """Opt-in admission check (validate_feed=True): non-finite
        values, unknown feed names, or dtype/rank drift vs the first
        accepted batch quarantine the batch BEFORE it reaches
        device_put — poison stopped at the door costs one skipped
        batch, not a guard trip and a rollback."""
        from ..data.pipeline import feed_signature, validate_feed_batch

        problems = validate_feed_batch(batch, self._feed_signature)
        if not problems:
            if self._feed_signature is None:
                self._feed_signature = feed_signature(batch)
            return False
        self.feed_stats["quarantined"] += 1
        if self.autopilot is not None:
            self.autopilot.note_quarantined_feed()
        self._emit("feed_quarantined", epoch=epoch, step=step,
                   quarantined_total=self.feed_stats["quarantined"],
                   problems=problems)
        return True

    def _autopilot_check(self, epoch: int, step: int) -> None:
        """Feed the freshly published telemetry window to the
        RecoveryController; escalate when it returns a trigger."""
        ap = self.autopilot
        if ap.halted or self.last_telemetry is None:
            return
        trigger = ap.observe_window(self.last_telemetry, epoch, step)
        if trigger is None:
            return
        if ap.rollbacks >= ap.cfg.max_rollbacks:
            self._recovery_halt(trigger, epoch, step,
                                reason="rollback_budget_exhausted")
        self._rollback(trigger, epoch, step)

    def _rollback(self, trigger: dict, epoch: int, step: int) -> None:
        """Rung 2+3: restore the newest loadable verified-good serial
        in process, quarantine the data window the diverged timeline
        consumed, and unwind the epoch loop to the restored cursor."""
        from ..resilience.errors import CheckpointError

        ap = self.autopilot
        target = None
        with self.goodput_ledger.phase("recovery", label="rollback"):
            # a background save may still reference the live arrays —
            # and a save of the POISONED state must not land after the
            # restore and become the newest serial
            self._await_pending(surface=False)
            for serial, e_r, s_r in reversed(ap.verified_serials()):
                path = os.path.join(self._ckpt_root(),
                                    f"ckpt_{serial}")
                try:
                    self._load_checkpoint(path)
                except CheckpointError as e:
                    self._emit("ckpt_fallback", serial=serial,
                               error=e.as_dict())
                    ap.forget_serial(serial)
                    continue
                target = (serial, e_r, s_r)
                break
        if target is None:
            self._recovery_halt(trigger, epoch, step,
                                reason="no_verified_checkpoint")
        serial, e_r, s_r = target
        window = {"from_epoch": e_r, "from_step": s_r,
                  "to_epoch": epoch, "to_step": step}
        ap.on_rollback(window)
        self._window_dirty = False  # the restored state is clean
        self._in_recovery = True
        backoff = self._apply_lr_backoff()
        self._emit("recovery_rollback", serial=serial, trigger=trigger,
                   rollbacks=ap.rollbacks, budget=ap.cfg.max_rollbacks,
                   lr_backoff=backoff, **window)
        self._emit("data_quarantine",
                   batches=(window["to_step"] - window["from_step"]
                            if window["from_epoch"] == window["to_epoch"]
                            else None), **window)
        raise _RollbackSignal(e_r, s_r)

    def _recovery_halt(self, trigger: dict, epoch: int, step: int,
                       reason: str) -> None:
        """Rung 4: stop deliberately with full provenance (plus a
        FlightRecorder bundle when pillar 9 is attached) instead of
        guard-skipping updates forever."""
        from ..resilience.errors import TrainingDivergedError

        ap = self.autopilot
        ap.halted = True
        ap.last_trigger = dict(trigger)
        bundle = None
        if self.flight_recorder is not None:
            bundle = self.flight_recorder.record(
                "training_diverged", force=True,
                context={"trigger": trigger, "reason": reason,
                         "epoch": epoch, "step": step,
                         "rollbacks": ap.rollbacks,
                         "budget": ap.cfg.max_rollbacks,
                         "quarantine_windows": ap.quarantine_windows})
        self._emit("recovery_halt", reason=reason, epoch=epoch,
                   step=step, trigger=trigger, rollbacks=ap.rollbacks,
                   budget=ap.cfg.max_rollbacks, flight_bundle=bundle)
        raise TrainingDivergedError(
            f"training diverged at epoch {epoch} step {step} "
            f"(signal: {trigger.get('signal')}); halting: {reason} "
            f"after {ap.rollbacks}/{ap.cfg.max_rollbacks} rollbacks",
            reason=reason, trigger=trigger, epoch=epoch, step=step,
            rollbacks=ap.rollbacks, budget=ap.cfg.max_rollbacks,
            quarantine_windows=list(ap.quarantine_windows),
            first_nonfinite_op=trigger.get("first_nonfinite_op"),
            flight_bundle=bundle)

    def _apply_lr_backoff(self):
        """Optional rung-3 extra: scale every `.learning_rate`
        variable (optimizer.py names them `<op>.learning_rate`) after
        a restore.  Off by default — the chaos parity proof requires
        re-entry bit-identical to a run that never diverged."""
        factor = self.autopilot.cfg.lr_backoff
        if factor is None or factor == 1.0:
            return None
        scaled = []
        with scope_guard(self.scope):
            for name in list(self.train_program.global_block().vars):
                if not name.endswith(".learning_rate"):
                    continue
                arr = self.scope.find_var(name)
                if arr is None:
                    continue
                host = np.asarray(arr)
                self.scope.set_var(
                    name, host * np.asarray(factor, dtype=host.dtype))
                scaled.append(name)
        return ({"factor": factor, "vars": scaled} if scaled else None)

    # -- goodput (observe pillar 8) --------------------------------------
    def _progress_path(self) -> str:
        return os.path.join(self._ckpt_root(), "__progress__.json")

    def _write_progress(self, epoch: int, step: int) -> None:
        """Atomically record how many steps actually EXECUTED (the
        crash cursor a relaunch reads to count replay badput — steps
        between the resumed checkpoint and this high-water mark run
        twice).  Accounting only: best-effort, never fails a step."""
        try:
            os.makedirs(self._ckpt_root(), exist_ok=True)
            tmp = self._progress_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"epoch": epoch, "step": step}, f)
            os.replace(tmp, self._progress_path())
        except OSError:
            pass

    def _read_progress(self):
        if not self.checkpoint_cfg:
            return None
        try:
            with open(self._progress_path()) as f:
                d = json.load(f)
            return (int(d["epoch"]), int(d["step"]))
        except (OSError, ValueError, KeyError):
            return None

    def goodput(self, mfu: Optional[float] = None):
        """The pillar-8 wall-clock decomposition of this trainer's
        train() time: GoodputLedger.report() — Σ categories == wall,
        goodput fraction, replay badput, `effective_mfu` when a
        headline MFU is passed, and the heartbeat-skew straggler
        estimate when a health plane is active."""
        from ..resilience import health as gang_health

        plane = gang_health.get_health_plane()
        skew = plane.skew() if plane is not None else None
        return self.goodput_ledger.report(mfu=mfu, skew=skew)

    def _drain(self, serial: int, epoch: int, step: int):
        """Preemption drain (docs/RESILIENCE.md): called at a step
        boundary once the drain flag is up.  Awaits any in-flight async
        save (its failure is logged, not fatal — the emergency save
        below is the one that must land), writes a SYNCHRONOUS
        emergency checkpoint, emits the drain events, and raises
        TrainingPreempted carrying the distinct exit code."""
        from ..resilience import preempt
        from ..resilience.errors import TrainingPreempted

        reason = preempt.drain_reason() or "requested"
        self._emit("preempt_drain", reason=reason, epoch=epoch,
                   step=step)
        em_serial = None
        if self.checkpoint_cfg:
            self._await_pending(surface=False)
            self._save_checkpoint(serial, epoch, step, emergency=True,
                                  force_sync=True)
            em_serial = serial
            self._emit("ckpt_emergency", serial=serial, epoch=epoch,
                       step=step)
        # the drain request is CONSUMED by this drain: the flag is
        # process-global, so leaving it set would instantly re-drain a
        # train() call that resumes in-process after catching
        # TrainingPreempted (the subprocess relaunch path never sees
        # the stale flag — this is for embedders/tests)
        preempt.clear_drain()
        raise TrainingPreempted(
            f"training drained after preemption ({reason}) at epoch "
            f"{epoch} step {step}; emergency checkpoint serial: "
            f"{em_serial}", reason=reason, epoch=epoch, step=step,
            serial=em_serial, exit_code=preempt.PREEMPT_EXIT_CODE)

    # -- telemetry -------------------------------------------------------
    last_telemetry = None

    def _publish_telemetry(self, epoch: int, step: int, since):
        """Fetch the device accumulator (ONE host sync), attach the
        window's host runtime stats, and emit a `telemetry` event.
        With numerics enabled (observe pillar 6) the fetch joins the
        latched bitmap to the fluid op desc, and a window that latched
        a poisoned step emits a LOUD `nonfinite_provenance` event —
        the enriched form of a bare guard-trip counter."""
        from .. import observe

        tel = observe.fetch_telemetry(self.scope, reset=True,
                                      program=self.train_program)
        now = observe.runtime_stats.snapshot()
        if tel is None or tel.steps == 0:
            return now
        self.last_telemetry = tel
        # verified-good bookkeeping: the accumulator resets on fetch,
        # so the save path needs this window's verdict remembered
        self._window_dirty = bool(
            tel.skipped_update_steps or tel.nonfinite_grad_steps
            or tel.nonfinite_loss_steps
            or tel.first_nonfinite_op is not None)
        if self._event_log:
            delta = observe.runtime_stats.delta(since or {})
            self._event_log.telemetry_window(
                tel, epoch=epoch, step=step,
                compiles=delta["compiles"],
                compile_time_s=round(delta["compile_time_s"], 3),
                retraces=delta["retraces"],
                dispatches=delta["dispatches"],
                dispatch_time_s=round(delta["dispatch_time_s"], 4),
                peak_mem_bytes=observe.peak_memory_bytes())
            if tel.first_nonfinite_op is not None:
                wg, wr = observe.worst_update_ratio(tel.groups)
                self._event_log.event(
                    "nonfinite_provenance", epoch=epoch, step=step,
                    first_nonfinite_op=tel.first_nonfinite_op,
                    nonfinite_grad_steps=tel.nonfinite_grad_steps,
                    nonfinite_loss_steps=tel.nonfinite_loss_steps,
                    skipped_update_steps=tel.skipped_update_steps,
                    loss_scale=tel.loss_scale,
                    worst_update_ratio_group=wg,
                    worst_update_ratio=wr)
        return now

    # -- unified metrics export (observe pillar 7) ------------------------
    def metrics_registry(self):
        """One MetricsRegistry over this trainer's surfaces: the
        latest telemetry window (incl. per-group numerics when pillar
        6 is on), checkpoint-cost gauges, and the process-wide
        runtime/process/memory collectors.  Built once, cached."""
        if self._metrics_registry is None:
            from ..observe.registry import (MetricsRegistry, gauge,
                                            goodput_collector,
                                            recovery_collector,
                                            standard_collectors,
                                            telemetry_collector)

            reg = standard_collectors(MetricsRegistry())
            reg.register("training",
                         telemetry_collector(
                             lambda: self.last_telemetry))
            reg.register("goodput",
                         goodput_collector(lambda: self.goodput()))
            reg.register("recovery",
                         recovery_collector(
                             lambda: (self.autopilot.snapshot()
                                      if self.autopilot is not None
                                      else None)))

            def ckpt_collect():
                s = self.ckpt_stats
                return [
                    gauge("ckpt_saves_total", "checkpoints saved",
                          s["saves"]),
                    gauge("ckpt_blocking_ms",
                          "last blocking snapshot time",
                          s["blocking_ms"]),
                    gauge("ckpt_write_ms",
                          "last background write time",
                          s["write_ms"]),
                    gauge("ckpt_bytes", "last checkpoint bytes",
                          s["bytes"]),
                ]

            reg.register("checkpoint", ckpt_collect)
            self._metrics_registry = reg
        return self._metrics_registry

    def start_metrics_server(self, host: str = "127.0.0.1",
                             port: int = 0):
        """Opt-in /metrics + /healthz endpoint for a training run
        (binds localhost by default; port=0 = ephemeral).  Stopped by
        stop()."""
        if self._metrics_server is not None:
            return self._metrics_server
        from ..observe.registry import MetricsServer

        def health():
            return {"state": "training",
                    "last_window_steps":
                        (self.last_telemetry.steps
                         if self.last_telemetry is not None else 0),
                    "ckpt": dict(self.ckpt_stats)}

        self._metrics_server = MetricsServer(
            self.metrics_registry(), health_fn=health,
            host=host, port=port,
            alerts_fn=(self.alert_engine.state
                       if self.alert_engine is not None
                       else None)).start()
        return self._metrics_server

    def enable_alerts(self, rules=None, interval_s: float = 5.0,
                      flight_dir: Optional[str] = None,
                      recorder_config: Optional[dict] = None,
                      start: bool = True, **pack_kw):
        """Opt into observe pillar 9 on this trainer: an AlertEngine
        evaluating the training-health pack
        (`observe.trainer_rule_pack` — goodput drop, throughput
        regression, loss-spike/grad-norm z-scores, nonfinite steps,
        compile storm, gang skew; or explicit `rules`) over
        `metrics_registry()` every `interval_s` on a background
        thread.  With `flight_dir`, a FlightRecorder bundles
        diagnostics (event tail, metrics, goodput table, latched
        nonfinite provenance, watchdog state, thread stacks) on every
        firing alert AND on the step watchdog's hang verdict — the
        recorder's capture chains BEFORE the gang-poison on_hang.
        Pure host: zero device dispatches from the alert thread, no
        step-path hooks, step lowering byte-identical on vs off
        (tests/test_alerts.py pins it).  Stopped by stop()."""
        if self.alert_engine is not None:
            return self.alert_engine
        from ..observe.alerts import AlertEngine, trainer_rule_pack
        from ..observe.flightrec import FlightRecorder

        if rules is None:
            rules = trainer_rule_pack(**pack_kw)
        elif pack_kw:
            raise ValueError("pack_kw only applies to the default "
                             "rule pack")
        engine = AlertEngine(self.metrics_registry(), rules=rules,
                             interval_s=interval_s,
                             event_log=self._event_log)
        self.metrics_registry().register("alerts", engine.collector())
        if flight_dir is not None:
            self.flight_recorder = FlightRecorder(
                flight_dir, registry=self.metrics_registry(),
                event_log=self._event_log,
                goodput=self.goodput_ledger,
                telemetry_fetch=lambda: self.last_telemetry,
                watchdog=self._step_watchdog,
                **(recorder_config or {}))
            self.flight_recorder.attach_engine(engine)
        self.alert_engine = engine
        if self._metrics_server is not None:
            self._metrics_server.alerts_fn = engine.state
        if start:
            engine.start()
        return engine

    def save_params(self, dirname: str):
        with scope_guard(self.scope):
            fluid_io.save_params(self.exe, dirname,
                                 main_program=self.train_program)

    def save_inference_model(self, dirname: str,
                             feeded_var_names: Sequence[str],
                             target_vars: Sequence):
        with scope_guard(self.scope):
            fluid_io.save_inference_model(
                dirname, feeded_var_names, list(target_vars), self.exe,
                main_program=self.train_program)

    def stop(self):
        if self.alert_engine is not None:
            self.alert_engine.close()
        if self.flight_recorder is not None:
            self.flight_recorder.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        if self._ckpt_writer is not None:
            # flush the writer; a silently-dropped last checkpoint must
            # surface here, not on the next preemption
            self._ckpt_writer.close()
        self.exe.close()


class Inferencer:
    """reference contrib/trainer.py Inferencer: load params produced by a
    Trainer and run a forward network."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 shared_scope: Optional[Scope] = None):
        self.scope = shared_scope or Scope()
        self.program = Program()
        startup = Program()
        from ..core import unique_name

        with unique_name.guard(), program_guard(self.program, startup):
            outs = infer_func()
            self.outputs = (list(outs) if isinstance(outs, (list, tuple))
                            else [outs])
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(startup)
            fluid_io.load_params(self.exe, param_path,
                                 main_program=self.program)

    def infer(self, inputs: Dict[str, np.ndarray]):
        with scope_guard(self.scope):
            return self.exe.run(self.program, feed=inputs,
                                fetch_list=[o.name for o in self.outputs])
