"""Admission control: the robustness half of the serving engine.

A TPU serving frontend dies in one of three boring ways: an unbounded
queue grows until the process OOMs, expired requests burn device time
computing answers nobody is waiting for, or shutdown races in-flight
work and strands callers on futures that never resolve.  This module
owns all three:

- **bounded queue + fast-reject load shedding** — `check()` raises
  `QueueFullError` *at submit time* when the engine is at capacity;
  the caller gets a structured rejection in microseconds instead of a
  timeout after seconds (the TF-Serving batching-queue contract),
- **per-request deadlines** — `deadline_for()` stamps an absolute
  monotonic deadline on each request; the batcher drops expired
  requests *before* dispatch (`DeadlineExceededError`), never after,
- **health/drain state machine** — CREATED → RUNNING ⇄ DEGRADED →
  DRAINING → STOPPED.  Draining stops admission immediately but lets
  queued work finish, so a rolling restart never drops accepted
  requests,
- **circuit breaker** — `failure_threshold` CONSECUTIVE executor
  failures flip RUNNING → DEGRADED: submits fast-reject with
  `CircuitOpenError` (no queueing, no device contact) until the
  cooldown elapses, then exactly ONE half-open probe request is
  admitted; its success closes the breaker (back to RUNNING), its
  failure re-opens it for another cooldown.  A dead executor thus
  costs each caller microseconds, not a queue-full timeout, and
  recovery is automatic.

All serving errors derive from `ServingError` and carry a structured
`details` dict (`as_dict()`), so a frontend can serialize rejections
without parsing message strings.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

# -- state machine values (strings, so health() dicts are json-ready) ---
CREATED = "created"
RUNNING = "running"
DEGRADED = "degraded"   # breaker open: shedding, probing for recovery
DRAINING = "draining"
STOPPED = "stopped"


class ServingError(RuntimeError):
    """Base for structured serving rejections.

    `details` is machine-readable; `as_dict()` is the wire form a
    frontend returns to the client (and what tests assert on).

    `retryable` marks errors a ROUTER may transparently resubmit on
    another replica: the request itself is fine, the replica that held
    it is not (executor crash, scheduler death, evacuation for a
    weight roll).  Client-side rejections (bucket miss, deadline,
    queue full) stay non-retryable — resubmitting them elsewhere would
    produce the same answer or violate the caller's deadline.
    """

    kind = "serving_error"
    retryable = False

    def __init__(self, message: str, **details: Any):
        super().__init__(message)
        self.details = details

    def as_dict(self) -> Dict[str, Any]:
        out = {"error": self.kind, "message": str(self),
               "retryable": self.retryable}
        out.update(self.details)
        return out


class QueueFullError(ServingError):
    """Load shed: the bounded queue is at capacity (fast-reject)."""

    kind = "queue_full"


class DeadlineExceededError(ServingError):
    """The request's deadline expired while queued; it was dropped
    before dispatch (no device time was spent on it)."""

    kind = "deadline_exceeded"


class ServingClosedError(ServingError):
    """Submitted to an engine that is not RUNNING (not started yet,
    draining, or stopped)."""

    kind = "serving_closed"


class CircuitOpenError(ServingError):
    """Fast-reject: the engine is DEGRADED (breaker open after
    consecutive executor failures) and this request is not the
    half-open probe."""

    kind = "circuit_open"


class ExecutorFailureError(ServingError):
    """The batch dispatch (executor call) failed; every future in the
    batch resolves with this structured wrapper around the raw error.
    Retryable: the batch's requests were never at fault — a router may
    replay them on another replica."""

    kind = "executor_failure"
    retryable = True


class WeightReloadError(ServingError):
    """A hot weight reload was refused or broke its contract: shape/
    dtype mismatch vs the live parameters (a same-shape swap is what
    guarantees zero recompiles), an attempt to swap under live
    generations without evacuating first, or an XLA compile observed
    during a fleet roll."""

    kind = "weight_reload"


class CircuitBreaker:
    """Consecutive-failure circuit breaker (closed → open → half-open).

    Deliberately mechanism-only: the AdmissionController maps breaker
    state onto the serving state machine, the engine reports dispatch
    outcomes.  `clock` is injectable so tests drive the cooldown
    deterministically.  Thread-safe.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5,
                 cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown_s <= 0:
            raise ValueError("cooldown_s must be > 0")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self.opens = 0          # lifetime transition counters (stats)
        self.closes = 0

    @property
    def state(self) -> str:
        return self._state

    def record_failure(self) -> bool:
        """One executor failure; True when this flips the breaker OPEN
        (from closed at threshold, or a failed half-open probe)."""
        with self._lock:
            self._consecutive_failures += 1
            should_open = (
                self._state == self.HALF_OPEN
                or (self._state == self.CLOSED
                    and self._consecutive_failures
                    >= self.failure_threshold))
            if should_open:
                self._state = self.OPEN
                self._opened_at = self._clock()
                self.opens += 1
            return should_open

    def record_success(self) -> bool:
        """One executor success; True when this CLOSES an open/half-open
        breaker (recovery)."""
        with self._lock:
            self._consecutive_failures = 0
            if self._state in (self.OPEN, self.HALF_OPEN):
                self._state = self.CLOSED
                self._opened_at = None
                self.closes += 1
                return True
            return False

    def allow(self) -> bool:
        """May a request proceed right now?  CLOSED: yes.  OPEN: only
        once the cooldown elapsed — that request becomes THE half-open
        probe (state moves to HALF_OPEN so concurrent submits keep
        shedding until the probe resolves)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN and (
                    self._clock() - self._opened_at >= self.cooldown_s):
                self._state = self.HALF_OPEN
                return True
            return False

    def cooldown_remaining_s(self) -> float:
        with self._lock:
            if self._opened_at is None:
                return 0.0
            return max(0.0, self.cooldown_s
                       - (self._clock() - self._opened_at))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "failure_threshold": self.failure_threshold,
                    "opens": self.opens, "closes": self.closes}


class AdmissionController:
    """Admission decisions + the health/drain state machine.

    The controller is deliberately free of queue mechanics: the batcher
    reports its in-flight count and the controller answers admit/reject,
    so the policy is testable without threads.
    """

    def __init__(self, queue_capacity: int,
                 default_deadline_ms: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None):
        if queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if default_deadline_ms is not None and default_deadline_ms <= 0:
            raise ValueError("default_deadline_ms must be > 0")
        self.queue_capacity = int(queue_capacity)
        self.default_deadline_ms = default_deadline_ms
        self.breaker = breaker
        self._state = CREATED
        self._lock = threading.Lock()

    # -- state machine --------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    def start(self):
        with self._lock:
            if self._state != CREATED:
                raise ServingClosedError(
                    f"cannot start from state {self._state!r}",
                    state=self._state)
            self._state = RUNNING

    def begin_drain(self):
        with self._lock:
            if self._state in (DRAINING, STOPPED):
                return  # drain is idempotent
            if self._state not in (RUNNING, DEGRADED):
                raise ServingClosedError(
                    f"cannot drain from state {self._state!r}",
                    state=self._state)
            self._state = DRAINING

    def finish_drain(self):
        with self._lock:
            self._state = STOPPED

    # -- circuit breaker ------------------------------------------------
    def record_dispatch_result(self, ok: bool) -> Optional[str]:
        """Feed one executor outcome to the breaker and mirror its
        state onto the serving state machine.  Returns "opened" /
        "closed" on a transition (the engine emits the matching
        serving_breaker_* event), else None."""
        if self.breaker is None:
            return None
        if ok:
            if self.breaker.record_success():
                with self._lock:
                    if self._state == DEGRADED:
                        self._state = RUNNING
                return "closed"
            return None
        if self.breaker.record_failure():
            with self._lock:
                if self._state == RUNNING:
                    self._state = DEGRADED
            return "opened"
        return None

    # -- admission ------------------------------------------------------
    def check(self, inflight: int):
        """Admit one request given the current in-flight count, or
        raise the structured rejection.  Called under the batcher's
        lock, so the count cannot race past capacity."""
        if self._state == DEGRADED:
            # breaker open: shed in microseconds UNLESS this request is
            # the half-open probe (capacity still applies to the probe)
            if not self.breaker.allow():
                raise CircuitOpenError(
                    "engine degraded: executor failing; request shed "
                    "(circuit open)", state=self._state,
                    breaker=self.breaker.snapshot(),
                    retry_after_s=round(
                        self.breaker.cooldown_remaining_s(), 3))
        elif self._state != RUNNING:
            raise ServingClosedError(
                f"engine is {self._state}; not accepting requests",
                state=self._state)
        if inflight >= self.queue_capacity:
            raise QueueFullError(
                f"queue at capacity ({self.queue_capacity}); request "
                "shed", capacity=self.queue_capacity, inflight=inflight)

    def deadline_for(self, deadline_ms: Optional[float],
                     now: Optional[float] = None) -> Optional[float]:
        """Absolute monotonic deadline for a request, or None when
        neither the request nor the engine sets one."""
        ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        if ms is None:
            return None
        if ms <= 0:
            raise ValueError("deadline_ms must be > 0")
        return (now if now is not None else time.monotonic()) + ms / 1e3

    def health(self, **extra: Any) -> Dict[str, Any]:
        out = {"state": self._state, "capacity": self.queue_capacity}
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        out.update(extra)
        return out
