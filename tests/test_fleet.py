"""Serving-fleet resilience suite (ISSUE 14) — the pinned chaos proofs.

The load-bearing properties, each proven by injecting its fault:

- **decode failover is invisible**: fault-inject one replica
  mid-generation under offered load → every affected request completes
  on a survivor with output TOKEN-IDENTICAL (greedy) to an
  uninterrupted control engine, zero client-visible failures, zero
  post-warmup compiles fleet-wide (the PR 12 preemption proof lifted
  across replica boundaries).
- **hot reload drops nothing**: `fleet.reload()` under sustained load
  rejects zero requests, performs zero recompiles (same-shape assert),
  and responses carry the new model version after the roll.
- **every boundary crossing is structured**: evacuation descriptors,
  retryable replica-failure errors, fleet saturation fast-rejects —
  all ServingError subclasses with `as_dict()`, all evented with
  replica_id stamps.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.core.executor import Executor, scope_guard
from paddle_tpu.models.decoder_lm import DecoderLM, make_prompts
from paddle_tpu.observe import read_events
from paddle_tpu.observe.monitoring import LatencyHistogram
from paddle_tpu.resilience import chaos
from paddle_tpu.serving import (BucketConfig, DecodeConfig, DecodeEngine,
                                DecodeReplicaFailedError, DecodeStats,
                                Fleet, FleetConfig, FleetSaturatedError,
                                ServingEngine, ServingStats,
                                WeightReloadError)

VOCAB = 48
PROMPTS = make_prompts(6, VOCAB, min_len=3, max_len=8, seed=21)
BUDGETS = [14, 12, 16, 11, 14, 12]


def _lm():
    return DecoderLM(vocab_size=VOCAB, n_layer=2, n_head=2, d_model=32,
                     d_inner=64, kv_dtype="float32", seed=7)


def _engine(**kw):
    # one prefill bucket: each engine start is exactly two compiles
    # (decode chunk + prefill), keeping the tier-1 wall cost low
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=48,
                       num_pages=24, prefill_buckets=(8,),
                       decode_chunk=2, kv_dtype="float32")
    return DecodeEngine(_lm(), cfg, memory_budget_bytes=False, **kw)


@pytest.fixture(scope="module")
def control_tokens():
    """The uninterrupted control: the same requests through one
    unkilled engine — greedy, so any fleet schedule must reproduce
    these tokens exactly."""
    eng = _engine().start()
    outs = [eng.generate(p, max_new_tokens=b, timeout_s=300).tolist()
            for p, b in zip(PROMPTS, BUDGETS)]
    eng.close()
    return outs


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    chaos.clear()


# -- the pinned chaos proof -------------------------------------------------

def test_replica_kill_failover_token_parity(control_tokens, tmp_path):
    """Kill one replica mid-generation under offered load: zero
    client-visible failures, every output token-identical to the
    control, committed prefixes verified, zero post-warmup compiles
    fleet-wide, the dead replica ejected.  With tracing on (ISSUE 15),
    the killed request keeps ONE trace_id across both replicas with a
    `failover` span naming the dead replica.  With alerts enabled
    (ISSUE 17), the kill flips the fleet_failover_rate rule to firing,
    the firing transition writes exactly ONE rate-limited flight
    bundle, and the rule resolves once the rate window slides past."""
    from paddle_tpu.observe import ReqTracer

    log_path = str(tmp_path / "fleet_events.jsonl")
    tracer = ReqTracer(sample_rate=1.0)
    engines = [_engine(), _engine()]
    fleet = Fleet(engines, FleetConfig(), log_path=log_path,
                  tracer=tracer).start()
    # pillar 9 rides the chaos proof: default SLO pack, no background
    # thread — the test drives evaluate() with an injected clock so
    # the rate windows are deterministic
    alerts = fleet.enable_alerts(start=False,
                                 flight_dir=str(tmp_path / "flight"),
                                 failover_window_s=30.0)
    assert alerts is fleet.alert_engine and not alerts.running
    alerts.evaluate(now=0.0)
    alerts.evaluate(now=1.0)
    assert alerts.firing() == []  # healthy fleet: nothing fires
    futs = [fleet.submit(p, max_new_tokens=b)
            for p, b in zip(PROMPTS, BUDGETS)]
    # mid-generation: wait until replica 0 has COMMITTED tokens, so at
    # least one failover carries a non-empty prefix to verify
    deadline = time.monotonic() + 60
    while (engines[0].stats.tokens_generated < 2
           and time.monotonic() < deadline):
        time.sleep(0.002)
    chaos.kill_replica(engines[0])
    resps = [f.result(300) for f in futs]
    outs = [r.tokens.tolist() for r in resps]
    snap = fleet.snapshot()
    assert outs == control_tokens, \
        "failover changed generated tokens (greedy identity broke)"
    assert snap["failed"] == 0
    assert snap["failovers"] >= 1, snap
    assert snap["parity_checked"] >= 1 and snap["parity_failed"] == 0
    assert snap["ejects"] == 1
    assert snap["post_warmup_compiles"] == 0, snap
    assert fleet.replicas[0].dead and not fleet.replicas[1].dead
    # requests that failed over say so in their provenance
    assert any(r.failovers >= 1 for r in resps)
    assert all(r.replica_id == 1 for r in resps if r.failovers)

    # ISSUE 15 trace continuity: the killed request's SINGLE trace_id
    # spans both replicas — its spans carry replica_id 0 AND 1, the
    # failover span names the dead replica and the survivor, and the
    # hop chain lands in the response
    killed = next(r for r in resps if r.failovers >= 1)
    assert killed.trace_id is not None
    assert 0 in killed.hops and killed.hops[-1] == 1, killed.hops
    traces = [t for t in tracer.traces()
              if t.trace_id == killed.trace_id]
    assert len(traces) == 1, "one trace_id per logical request"
    t = traces[0]
    assert set(t.replica_ids()) == {0, 1}, t.replica_ids()
    fo = t.find("failover")
    assert fo, t.span_names()
    assert fo[0].attrs["from_replica"] == 0
    assert fo[0].attrs["to_replica"] == 1
    names = t.span_names()
    for phase in ("join_wait", "dispatch", "evacuated", "complete"):
        assert phase in names, (phase, names)
    # chrome export renders the hop across replica rows (router + 2)
    ct = tracer.export_chrome_trace()
    rows = {e["pid"] for e in ct["traceEvents"] if e.get("ph") == "X"
            and e["args"].get("trace_id") == killed.trace_id}
    assert len(rows) >= 3, rows

    # ISSUE 17: the kill must flip the failover-rate rule to firing
    # and write exactly one rate-limited diagnostic bundle
    alerts.evaluate(now=2.0)
    assert "fleet_failover_rate" in alerts.firing(), alerts.state()
    sig = alerts.signals()["fleet_failover_rate"]
    assert sig["firing"] is True and sig["value"] > 0.0
    # the dead replica also trips fleet_replicas_down in the SAME
    # pass — its bundle is rate-limited: exactly one hits disk
    assert "fleet_replicas_down" in alerts.firing()
    rec = fleet.flight_recorder
    assert len(rec.bundles) == 1 and rec.suppressed == 1, \
        rec.snapshot()
    bundle = rec.bundles[0]
    assert os.path.basename(bundle) == \
        "bundle_001_alert_fleet_failover_rate"
    import json as _json

    man = _json.load(open(os.path.join(bundle, "MANIFEST.json")))
    assert man["context"]["rule"] == "fleet_failover_rate"
    assert man["errors"] == {}
    for f_ in ("metrics.json", "alerts.json", "reqtrace.json",
               "events_tail.jsonl", "stacks.txt"):
        assert f_ in man["files"], man["files"]
    cap = _json.load(open(os.path.join(bundle, "metrics.json")))
    assert sum(s["value"] for s in
               cap["fleet_failovers_total"]["samples"]) >= 1
    # the alerts family is on the fleet's /metrics surface
    text = fleet.metrics_registry().prometheus_text()
    assert 'alerts_firing{rule="fleet_failover_rate"' in text
    # still breaching inside the window: no flapping, no new bundle
    alerts.evaluate(now=3.0)
    assert "fleet_failover_rate" in alerts.firing()
    assert len(rec.bundles) == 1
    # recovery: the 30 s rate window slides past the kill → resolved
    alerts.evaluate(now=40.0)
    assert "fleet_failover_rate" not in alerts.firing(), \
        alerts.state()
    assert alerts.signals()["fleet_failover_rate"]["state"] == \
        "inactive"
    fleet.close()

    # satellite: replica_id stamps every engine event in the shared
    # log; the fleet lifecycle + failover events are present
    events = read_events(log_path)
    kinds = [e["event"] for e in events]
    assert "serving_fleet_start" in kinds
    assert "serving_fleet_failover" in kinds
    assert "serving_fleet_eject" in kinds
    replica_events = [e for e in events
                      if e["event"].startswith("serving_decode")]
    assert replica_events, kinds
    assert all("replica_id" in e for e in replica_events)
    assert {e["replica_id"] for e in replica_events} == {0, 1}
    # ISSUE 17: the alert lifecycle and the bundle write are evented
    # into the SAME shared log (registered kinds, strict-mode clean)
    fired = [e for e in events if e["event"] == "alert_firing"]
    assert {e["rule"] for e in fired} >= {"fleet_failover_rate",
                                          "fleet_replicas_down"}
    resolved = [e for e in events if e["event"] == "alert_resolved"]
    assert "fleet_failover_rate" in {e["rule"] for e in resolved}
    flights = [e for e in events if e["event"] == "flight_record"]
    assert len(flights) == 1
    assert flights[0]["reason"] == "alert_fleet_failover_rate"
    assert flights[0]["path"] == bundle


def test_hot_reload_under_load(control_tokens):
    """fleet.reload() during sustained load: zero dropped requests,
    zero recompiles, token parity before/after (same weights), and a
    post-roll response tagged with the new model version."""
    engines = [_engine(), _engine()]
    fleet = Fleet(engines, FleetConfig()).start()
    with tempfile.TemporaryDirectory() as d:
        with scope_guard(engines[0].scope):
            fluid.io.save_sharded(
                Executor(), d,
                main_program=engines[0].model.step["main"])
        futs = [fleet.submit(p, max_new_tokens=b)
                for p, b in zip(PROMPTS, BUDGETS)]
        info = fleet.reload(d)
        outs = [f.result(300).tokens.tolist() for f in futs]
    assert outs == control_tokens, "reload perturbed in-flight tokens"
    assert info["version"] == 1 and info["compiles"] == 0
    assert info["pause_ms_max"] > 0
    snap = fleet.snapshot()
    assert snap["failed"] == 0
    assert snap["reloads"] == 2 and snap["reload_pause_ms"] > 0
    assert snap["post_warmup_compiles"] == 0, snap
    post = fleet.generate(PROMPTS[0], max_new_tokens=4, timeout_s=300)
    assert post.model_version == 1
    assert post.tokens.tolist() == control_tokens[0][:4]
    assert fleet.model_version == 1
    assert all(e.model_version == 1 for e in engines)
    fleet.close()


# -- structured evacuation / failure surface --------------------------------

@pytest.mark.slow
def test_evacuate_returns_requeueable_descriptors(control_tokens):
    eng = _engine().start()
    futs = [eng.submit(p, max_new_tokens=b)
            for p, b in zip(PROMPTS[:3], BUDGETS[:3])]
    deadline = time.monotonic() + 60
    while (eng.stats.tokens_generated < 2
           and time.monotonic() < deadline):
        time.sleep(0.002)
    descs = eng.evacuate()
    assert len(descs) == 3
    for f, d, p, b in zip(futs, descs, PROMPTS[:3], BUDGETS[:3]):
        exc = f.exception(timeout=10)
        assert isinstance(exc, DecodeReplicaFailedError)
        wire = exc.as_dict()
        assert wire["error"] == "decode_replica_failed"
        assert wire["retryable"] is True
        assert wire["reason"] == "evacuated"
        assert wire["descriptor"]["prompt"] == [int(t) for t in p]
        assert wire["descriptor"]["max_new_tokens"] == b
        assert (wire["descriptor"]["committed_tokens"]
                == len(wire["descriptor"]["generated"]))
    assert eng.stats.snapshot()["evacuations"] == 3
    # the engine keeps serving, and a requeued descriptor regenerates
    # token-identically, reproducing the committed prefix
    d0 = descs[0]
    regen = eng.generate(np.asarray(d0["prompt"]),
                         max_new_tokens=d0["max_new_tokens"],
                         timeout_s=300).tolist()
    assert regen == control_tokens[0]
    assert regen[:d0["committed_tokens"]] == d0["generated"]
    eng.close()


@pytest.mark.slow
def test_scheduler_death_resolves_futures_structured():
    eng = _engine()
    eng.set_replica_id(7)
    eng.start()
    futs = [eng.submit(p, max_new_tokens=b)
            for p, b in zip(PROMPTS[:2], BUDGETS[:2])]
    chaos.kill_replica(eng)
    for f in futs:
        exc = f.exception(timeout=60)
        assert isinstance(exc, DecodeReplicaFailedError)
        wire = exc.as_dict()
        assert wire["retryable"] is True
        assert wire["reason"] == "scheduler_failed"
        assert "ChaosKilled" in wire["cause"]
        assert wire["replica_id"] == 7
        assert wire["descriptor"]["prompt"]
    # a dead scheduler stops accepting with the structured closed error
    from paddle_tpu.serving import ServingClosedError

    with pytest.raises(ServingClosedError):
        eng.submit(PROMPTS[0], max_new_tokens=2)
    eng.close()


@pytest.mark.slow
def test_reload_shape_mismatch_rejected():
    eng = _engine().start()
    before = eng.generate(PROMPTS[0], max_new_tokens=3,
                          timeout_s=300).tolist()
    bad = {n: np.zeros((3, 3), np.float32) for n in eng._params}
    with pytest.raises(WeightReloadError) as e:
        eng.reload(bad)
    wire = e.value.as_dict()
    assert wire["error"] == "weight_reload" and wire["mismatched"]
    assert eng.model_version == 0  # old weights keep serving
    assert eng.generate(PROMPTS[0], max_new_tokens=3,
                        timeout_s=300).tolist() == before
    # refusing to swap under a live generation is also structured
    fut = eng.submit(PROMPTS[2], max_new_tokens=30)
    good = {n: np.asarray(v) for n, v in eng._params.items()}
    with pytest.raises(WeightReloadError) as e2:
        eng.reload(good)
    assert "evacuate" in str(e2.value)
    fut.result(300)
    eng.close()


# -- routing: saturation + hedging ------------------------------------------

@pytest.mark.slow
def test_fleet_saturated_fast_reject_structured():
    def tiny():
        cfg = DecodeConfig(num_slots=1, page_size=4, max_len=48,
                           num_pages=12, prefill_buckets=(8, 16),
                           decode_chunk=2, kv_dtype="float32")
        return DecodeEngine(_lm(), cfg, memory_budget_bytes=False,
                            queue_capacity=1)

    engines = [tiny(), tiny()]
    fleet = Fleet(engines, FleetConfig()).start()
    futs = [fleet.submit(p, max_new_tokens=20) for p in PROMPTS[:2]]
    with pytest.raises(FleetSaturatedError) as e:
        fleet.submit(PROMPTS[2], max_new_tokens=20)
    wire = e.value.as_dict()
    assert wire["error"] == "fleet_saturated"
    assert {r["reject"] for r in wire["rejects"]} == {"queue_full"}
    assert len(wire["replicas"]) == 2
    assert fleet.stats.snapshot()["saturated"] == 1
    for f in futs:  # accepted work still completes
        assert len(f.result(300).tokens) == 20
    fleet.close()


@pytest.mark.slow
def test_hedging_beats_straggler_replica(control_tokens):
    from paddle_tpu.observe import ReqTracer

    tracer = ReqTracer(sample_rate=1.0)
    engines = [_engine(), _engine()]
    fleet = Fleet(engines, FleetConfig(hedge_after_ms=100),
                  tracer=tracer).start()
    # replica 0 (first pick: least-loaded tie breaks on id) stalls for
    # 2 s; the hedge duplicate on replica 1 must win long before that
    chaos.delay_replica(engines[0], 2.0)
    t0 = time.monotonic()
    resp = fleet.generate(PROMPTS[0], max_new_tokens=4, timeout_s=300)
    elapsed = time.monotonic() - t0
    assert resp.tokens.tolist() == control_tokens[0][:4]
    assert resp.replica_id == 1
    assert elapsed < 1.9, f"hedge did not beat the straggler: {elapsed}"
    snap = fleet.stats.snapshot()
    assert snap["hedges"] >= 1 and snap["hedge_wins"] >= 1
    fleet.close()  # drains: the straggler attempt resolves before this
    #                returns, landing the loser's `abandoned` marker
    # ISSUE 15: the hedged request is ONE trace — the hedge fires, the
    # winner completes on replica 1, and the loser (delayed replica 0)
    # is marked abandoned when its late work surfaces
    t = tracer.trace(resp.trace_id)
    assert t is not None and resp.hedged
    assert t.has("hedge"), t.span_names()
    complete = t.find("complete")
    assert complete and complete[0].attrs["replica_id"] == 1
    abandoned = t.find("abandoned")
    assert abandoned, t.span_names()
    assert abandoned[0].attrs["replica_id"] == 0


# -- cross-replica stats aggregation ----------------------------------------

def test_decode_stats_merge_sums_and_rejects_mismatch():
    a, b = DecodeStats(), DecodeStats()
    a.record_submit()
    b.record_submit()
    b.record_submit()
    a.record_prefill(2, [1.0, 2.0])
    b.record_prefill(1, [3.0])
    a.record_decode(4, 2, 2, 6, 5, 10, 12.0)
    b.record_decode(2, 1, 2, 2, 8, 10, 4.0)
    a.record_preemption()
    b.record_reload(7.5)
    a.merge(b)
    s = a.snapshot()
    assert s["submitted"] == 3
    assert s["prefill_joins"] == 3
    assert s["tokens_generated"] == (2 + 6) + (1 + 2)
    assert s["ttft_ms"]["count"] == 3
    assert s["tpot_ms"]["count"] == 2
    assert s["peak_pages_in_use"] == 8
    assert s["reloads"] == 1 and s["reload_pause_ms"] == 7.5
    # exact weighted occupancy: (2*4 + 1*2) / (2*4 + 2*2)
    assert s["slot_occupancy"] == round(10 / 12, 4)
    # config mismatches are rejected, not silently mis-merged
    with pytest.raises(TypeError):
        ServingStats().merge(DecodeStats())
    odd = DecodeStats()
    odd.ttft_ms = LatencyHistogram(bins_per_decade=10)
    with pytest.raises(ValueError):
        DecodeStats().merge(odd)


def test_serving_stats_merge():
    a, b = ServingStats(), ServingStats()
    for s_ in (a, b):
        s_.record_submit(3)
        s_.record_batch(2, 4, 8.0, 16.0, 5.0)
        s_.record_done(11.0)
    b.record_shed()
    b.record_reload(3.25)
    a.merge(b)
    s = a.snapshot()
    assert s["submitted"] == 2 and s["completed"] == 2
    assert s["shed"] == 1 and s["batches"] == 2
    assert s["reloads"] == 1 and s["reload_pause_ms"] == 3.25
    assert s["e2e_ms"]["count"] == 2 and s["exec_ms"]["count"] == 2
    assert s["batch_occupancy"] == round(4 / 8, 4)


# -- the serving (single-shot) fleet kind -----------------------------------

@pytest.fixture(scope="module")
def mlp_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fleet_mlp"))
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = fluid.layers.data("x", shape=[16], append_batch_size=True)
        h = fluid.layers.fc(x, size=32, act="relu")
        pred = fluid.layers.fc(h, size=4)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


@pytest.mark.slow
def test_serving_fleet_failover_and_reload(mlp_dir):
    """The single-shot kind: a killed dispatch fails over to the other
    replica (same answer), and a rolling reload swaps the live
    predictor params with zero recompiles and a version tag."""
    rng = np.random.RandomState(3)
    xs = rng.rand(8, 16).astype(np.float32)
    ref = fluid.Predictor(mlp_dir)
    refs = [ref.run({"x": xs[i:i + 1]})[0][0] for i in range(8)]

    def mk():
        return ServingEngine(mlp_dir, {"x": np.zeros(16, np.float32)},
                             buckets=BucketConfig((1, 2, 4)),
                             max_wait_ms=2.0)

    engines = [mk(), mk()]
    fleet = Fleet(engines, FleetConfig()).start()
    chaos.kill_replica(engines[0])  # next dispatch on 0 fails once
    resps = [fleet.infer({"x": xs[i]}, timeout_s=120) for i in range(8)]
    for i, r in enumerate(resps):
        np.testing.assert_allclose(r.outputs[0], refs[i], rtol=1e-5,
                                   atol=1e-6)
    snap = fleet.snapshot()
    assert snap["failed"] == 0 and snap["failovers"] >= 1
    assert snap["post_warmup_compiles"] == 0, snap
    # neither replica died (a failed dispatch is transient): both route
    assert all(not h.dead for h in fleet.replicas)

    info = fleet.reload(
        {n: np.asarray(v)
         for n, v in engines[0].predictor._params.items()})
    assert info["version"] == 1 and info["compiles"] == 0
    r = fleet.infer({"x": xs[0]}, timeout_s=120)
    assert r.model_version == 1
    np.testing.assert_allclose(r.outputs[0], refs[0], rtol=1e-5,
                               atol=1e-6)
    assert fleet.snapshot()["post_warmup_compiles"] == 0
    fleet.close()
