"""Watchdog + retry: deadline-guarded compile/dispatch and bounded
exponential-backoff retries.

Generalizes bench.py's two hard-won lessons into reusable machinery:

- backend init can HANG, not just error (r03: driver rc=124 with no
  JSON line) — so `probe_backend` runs the init + one tiny matmul in a
  SUBPROCESS with a hard timeout; an in-process try/except never fires
  on a hang,
- a hung XLA compile/dispatch must become a recorded error, not eat
  the caller's whole budget — `Deadline` is the SIGALRM watchdog
  bench.py wrapped each model in, now shared by bench, contrib.Trainer
  (`step_deadline_s`) and `ServingEngine.start()` (warmup deadline).

`Deadline` uses SIGALRM on the main thread and a TIMER-THREAD
fallback elsewhere (`PyThreadState_SetAsyncExc` into the guarded
thread — CPython accepts only a CLASS there, so the fallback raises a
dynamically derived WatchdogTimeout subclass carrying the region name
in its no-arg constructor).  Both modes are best-effort: a C call
that never re-enters the interpreter cannot be interrupted.

`DispatchWatchdog` is the training-step layer on top: per-step
budgets that distinguish a FIRST COMPILE (no dispatch has ever
completed — XLA legitimately takes minutes; the long `compile_grace_s`
budget applies) from a HUNG STEP (a previously-working step stopped
returning — the dead-peer-inside-a-collective signature; the tight
`step_deadline_s` applies), using the host-side `runtime_stats`
compile/dispatch counters.  On timeout it emits a `step_hang` event
(and fires `on_hang` — contrib.Trainer poisons the gang there) BEFORE
raising the structured `StepHangError`, so the abort is observable
even if the raise itself gets swallowed by a dying process.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

from .errors import RetriesExhaustedError, StepHangError, WatchdogTimeout


def _timer_exc_class(what: str, seconds: float):
    """A WatchdogTimeout subclass whose no-arg constructor carries the
    region context — PyThreadState_SetAsyncExc instantiates the class
    itself and rejects pre-built instances."""

    class _TimerDeadline(WatchdogTimeout):
        def __init__(self):
            super().__init__(
                f"{what} exceeded {seconds:.0f}s deadline "
                f"(timer-thread watchdog)", what=what,
                deadline_s=seconds, mode="timer")

    _TimerDeadline.__name__ = "WatchdogTimeout"
    return _TimerDeadline


class Deadline:
    """Wall-clock watchdog around a region: raises `WatchdogTimeout`
    (with the region name in `details`) when the body exceeds
    `seconds`.  Main thread: SIGALRM.  Other threads: a timer thread
    injects the exception via PyThreadState_SetAsyncExc (`mode`
    records which).  Best-effort — a C call that never re-enters the
    interpreter cannot be interrupted; `seconds <= 0` disables."""

    def __init__(self, seconds: float, what: str = "guarded region"):
        self.seconds = float(seconds)
        self.what = what
        self.armed = False
        self.mode: Optional[str] = None
        self._old = None
        self._timer: Optional[threading.Timer] = None
        self._done = False
        self._lock = threading.Lock()

    def __enter__(self):
        import signal

        if self.seconds <= 0:
            return self
        if threading.current_thread() is threading.main_thread():
            def _fire(signum, frame):
                raise WatchdogTimeout(
                    f"{self.what} exceeded {self.seconds:.0f}s deadline",
                    what=self.what, deadline_s=self.seconds,
                    mode="sigalrm")

            self._old = signal.signal(signal.SIGALRM, _fire)
            # SIGALRM takes whole seconds; round up so Deadline(0.5) fires
            signal.alarm(max(1, int(-(-self.seconds // 1))))
            self.armed = True
            self.mode = "sigalrm"
            return self

        # off the main thread: timer-thread fallback (the pre-gang
        # behavior was a silent no-op — a watchdog that only works on
        # one thread cannot guard supervisor/serving workers)
        import ctypes

        tid = threading.get_ident()
        exc_cls = _timer_exc_class(self.what, self.seconds)

        def _expire():
            with self._lock:
                if self._done:
                    return
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(exc_cls))

        self._timer = threading.Timer(self.seconds, _expire)
        self._timer.daemon = True
        self._timer.start()
        self.armed = True
        self.mode = "timer"
        return self

    def __exit__(self, *exc):
        import signal

        if not self.armed:
            return False
        if self.mode == "sigalrm":
            signal.alarm(0)
            signal.signal(signal.SIGALRM, self._old)
        else:
            with self._lock:
                self._done = True
            if self._timer is not None:
                self._timer.cancel()
        self.armed = False
        return False


class DispatchWatchdog:
    """Per-step host deadline that knows the difference between "XLA
    is still compiling" and "a working step hung".

    The host cannot see inside a blocked dispatch, so the proxy is the
    runtime_stats counters: until this process has COMPLETED at least
    one dispatch since the watchdog was created, a guarded region is
    classified `first_compile` and gets `compile_grace_s`; afterwards
    every region is a steady-state step and gets `step_deadline_s` —
    on a synchronous gang, the step that stops returning after steps
    were flowing is the hung-collective signature.  Each timeout emits
    a `step_hang` event (runtime_stats deltas attached), calls
    `on_hang(fields)` (Trainer poisons the gang here), then raises
    `StepHangError`.  `regions` records every guarded region's budget
    and verdict — the test-observable surface."""

    def __init__(self, step_deadline_s: float,
                 compile_grace_s: Optional[float] = None,
                 event_log=None,
                 on_hang: Optional[Callable[[Dict[str, Any]], None]]
                 = None):
        self.step_deadline_s = float(step_deadline_s)
        self.compile_grace_s = (
            float(compile_grace_s) if compile_grace_s is not None
            else max(self.step_deadline_s * 10.0, 60.0))
        self.event_log = event_log
        self.on_hang = on_hang
        self.regions: List[Dict[str, Any]] = []
        self._snap0: Optional[Dict[str, Any]] = None

    @contextmanager
    def guard(self, what: str = "train step"):
        from ..observe import runtime_stats

        snap = runtime_stats.snapshot()
        if self._snap0 is None:
            self._snap0 = snap
        seen_dispatch = snap["dispatches"] > self._snap0["dispatches"]
        kind = "step" if seen_dispatch else "first_compile"
        budget = (self.step_deadline_s if seen_dispatch
                  else self.compile_grace_s)
        rec: Dict[str, Any] = {"what": what, "kind": kind,
                               "budget_s": budget, "hang": None}
        self.regions.append(rec)
        try:
            with Deadline(budget, what=what):
                yield rec
        except WatchdogTimeout as e:
            delta = runtime_stats.delta(snap)
            hang_kind = ("first_compile" if kind == "first_compile"
                         else "hung_step")
            rec["hang"] = hang_kind
            fields = {"what": what, "kind": hang_kind,
                      "budget_s": budget,
                      "compiles_delta": delta["compiles"],
                      "dispatches_delta": delta["dispatches"],
                      "retraces_delta": delta["retraces"]}
            if self.event_log is not None:
                try:
                    # the verdict field is `hang_kind` in the event
                    # record ("kind" is the event method's own
                    # positional and cannot ride **fields)
                    self.event_log.event(
                        "step_hang",
                        **{("hang_kind" if k == "kind" else k): v
                           for k, v in fields.items()})
                except Exception:  # noqa: BLE001
                    pass
            if self.on_hang is not None:
                try:
                    self.on_hang(dict(fields))
                except Exception:  # noqa: BLE001 — abort must proceed
                    pass
            raise StepHangError(
                f"{what} exceeded its {budget:.0f}s "
                f"{'compile-grace' if hang_kind == 'first_compile' else 'step'}"
                f" budget ({hang_kind}); compiles+{delta['compiles']} "
                f"dispatches+{delta['dispatches']} inside the region",
                **fields) from e


def probe_backend(timeout_s: float,
                  platform_env: str = "BENCH_PLATFORM") -> Optional[str]:
    """Fail-fast backend health check: init the backend and run one
    tiny matmul in a SUBPROCESS with a hard timeout.  Returns None when
    healthy, else a short failure description (hang vs error is
    distinguished).  `platform_env` names the env var whose value, if
    set, pins jax_platforms inside the probe (the sitecustomize stomps
    JAX_PLATFORMS, so only the config route works)."""
    import os
    import subprocess
    import sys

    code = ("import os, jax;"
            f"plat = os.environ.get({platform_env!r});"
            "plat and jax.config.update('jax_platforms', plat);"
            "import jax.numpy as jnp;"
            "d = jax.devices();"
            "x = jnp.ones((128, 128), jnp.bfloat16);"
            "(x @ x).block_until_ready();"
            "print('BACKEND_OK', d[0].device_kind)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return (f"backend init did not complete within {timeout_s:.0f}s "
                f"(hang, not error)")
    if r.returncode != 0 or "BACKEND_OK" not in r.stdout:
        tail = (r.stderr or r.stdout).strip().splitlines()[-3:]
        return "backend init failed: " + " | ".join(tail)
    return None


def retry_call(fn: Callable, *, retries: int = 3,
               base_delay_s: float = 0.5, max_delay_s: float = 30.0,
               retry_on: Tuple[Type[BaseException], ...]
               = (Exception,),
               on_retry: Optional[Callable[[int, BaseException, float],
                                           None]] = None,
               sleep: Callable[[float], None] = time.sleep):
    """Call `fn()` with up to `retries` re-attempts on transient
    failure, sleeping base_delay_s * 2**attempt (capped) between
    attempts — deterministic backoff so tests can assert the schedule
    via an injected `sleep`.  `on_retry(attempt, exc, delay_s)` is the
    observation hook.  Raises `RetriesExhaustedError` (chaining the
    final error) when every attempt fails; non-retryable exceptions
    propagate immediately."""
    if retries < 0:
        raise ValueError("retries must be >= 0")
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as exc:  # noqa: PERF203 — retry loop
            last = exc
            if attempt == retries:
                break
            delay = min(base_delay_s * (2.0 ** attempt), max_delay_s)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
    raise RetriesExhaustedError(
        f"{retries + 1} attempt(s) failed; last error: {last}",
        attempts=retries + 1, last_error=f"{type(last).__name__}: {last}"
    ) from last


def backoff_schedule(retries: int, base_delay_s: float,
                     max_delay_s: float) -> Sequence[float]:
    """The deterministic delay sequence retry_call (and the gang
    supervisor) sleep between attempts — exposed so callers/tests can
    assert the schedule instead of re-deriving it."""
    return [min(base_delay_s * (2.0 ** a), max_delay_s)
            for a in range(retries)]
