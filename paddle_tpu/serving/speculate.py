"""Drafting layer for speculative decoding (ISSUE 20).

`DecodeEngine(speculate_k=k)` replaces the one-token-per-iteration
chunk loop with VERIFIED multi-token steps: a drafter proposes up to k
tokens per slot on the host, one fixed-shape verify dispatch (the step
program at folded batch S*(k+1), models/decoder_lm.py `verify`) scores
all of them, and greedy longest-accepted-prefix acceptance commits
1..k+1 tokens — bit-identical to the sequential engine, because the
verify forward IS the sequential forward at every drafted position.

Two interchangeable drafters behind one protocol:

- `NGramDrafter` (the default): host-side prompt-lookup drafting —
  propose the tokens that followed the most recent earlier occurrence
  of the current suffix n-gram in (prompt + generated).  Zero extra
  device cost, deterministic, and highly effective on repetitive
  streams (greedy LMs cycle; code/prose repeat).
- `ModelDrafter`: a small draft `DecoderLM` that shares the serving
  fleet's slot/pool conventions — its OWN KV pools at the ENGINE's
  exact (num_pages, page_size) geometry, addressed by the ENGINE's
  page tables, so join/leave/preempt/import keep both pools aligned
  with zero extra bookkeeping.  One fixed-k jitted chunk produces all
  k drafts in a single dispatch; prefill-on-join and the disagg
  import mirror into the draft pool through the same bucket ladder.

Draft-pool consistency needs NO rollback hook: the accepted-prefix
rows are exactly what a sequential draft run over the committed
stream would have written, and rejected-tail rows sit past every
slot's length — the next draft chunk overwrites them before any
attention can read them (the same rollback-as-no-write argument as
the target pool).

All drafter compiles happen inside `DecodeEngine.start()`'s warmup
window, so the zero-post-warmup-compile contract holds fleet-wide
across ANY accept pattern.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def ngram_propose(context, k: int, ngram: int = 3) -> List[int]:
    """Prompt-lookup drafting: find the MOST RECENT earlier occurrence
    of the trailing g-gram of `context` (g = ngram down to 1) and
    propose the <= k tokens that followed it.  Among the occurrences
    of a g-gram, the most recent one with a FULL k-token continuation
    wins over a nearer one truncated by the context end — in a
    short-period cycle the nearest match sits within k tokens of the
    tail and would cap every proposal below k, exactly the streams
    drafting serves best.  Pure and deterministic: same context ->
    same proposal, which is what makes speculative runs reproducible.
    Returns [] when nothing matches."""
    ctx = np.asarray(context, dtype=np.int64).ravel()
    n = int(ctx.size)
    k = int(k)
    if n < 2 or k < 1:
        return []
    for g in range(min(int(ngram), n - 1), 0, -1):
        # vectorized window match: starts 0..n-g-1, window == tail.
        # This scan runs per slot per verify round on the scheduler
        # thread — the numpy form is what keeps host drafting cheap
        # against the dispatch it races.
        tail = ctx[n - g:]
        match = ctx[:n - g] == tail[0]
        for j in range(1, g):
            match &= ctx[j:j + n - g] == tail[j]
        idx = np.nonzero(match)[0]
        if idx.size:
            full = idx[idx + g + k <= n]
            if full.size:
                start = int(full[-1])
            else:
                part = idx[idx + g < n]
                if not part.size:
                    continue
                start = int(part[-1])
            return [int(t) for t in ctx[start + g:start + g + k]]
    return []


class Drafter:
    """Protocol between DecodeEngine and a drafting strategy.

    The engine calls, always on its scheduler thread:
    - `start(engine)` inside the warmup window (compile here);
    - `on_prefill(engine, joiners, tokens, seq_len, last_idx)` after
      every successful prefill-on-join dispatch (same padded host
      buffers the engine dispatched);
    - `on_import(engine, slot_id)` after a disagg KV handoff seeds a
      slot on a decode-role worker;
    - `draft(engine, active_ids) -> (drafts (S, k) int32, draft_len
      (S,) int32)` once per verify round.  Proposals may be shorter
      than k (ragged draft_len) and the ENGINE caps them again to the
      slot's remaining budget — a drafter never worries about caps.
    """

    k: int = 0

    def start(self, engine) -> None:  # pragma: no cover - trivial
        pass

    def on_prefill(self, engine, joiners, tokens, seq_len,
                   last_idx) -> None:  # pragma: no cover - trivial
        pass

    def on_import(self, engine, slot_id) -> None:  # pragma: no cover
        pass

    def draft(self, engine, active_ids
              ) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Host-side prompt-lookup drafting (the default drafter): zero
    extra device cost, zero state — the context IS the slot's
    (prompt + generated) stream the scheduler already holds."""

    def __init__(self, k: int, ngram: int = 3):
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if int(ngram) < 1:
            raise ValueError(f"ngram must be >= 1, got {ngram}")
        self.k = int(k)
        self.ngram = int(ngram)

    def draft(self, engine, active_ids):
        s = engine.config.num_slots
        drafts = np.zeros((s, self.k), np.int32)
        draft_len = np.zeros((s,), np.int32)
        for i in active_ids:
            slot = engine._slots[i]
            ctx = np.concatenate([
                np.asarray(slot.req.prompt, np.int64).ravel(),
                np.asarray(slot.generated, np.int64)])
            follow = ngram_propose(ctx, self.k, self.ngram)
            draft_len[i] = len(follow)
            drafts[i, :len(follow)] = follow
        return drafts, draft_len


class ModelDrafter(Drafter):
    """A small draft DecoderLM following the target slot-for-slot.

    `model` is any models.decoder_lm.DecoderLM (its parameter names
    come out of the same `unique_name.guard()` discipline as the
    target's, so checkpoints load with the normal io path).  Pools are
    allocated at the ENGINE's exact page geometry and addressed by the
    ENGINE's page tables — the draft pool is a shadow of the target
    pool, kept aligned for free by every join/preempt/import.

    A draft model with the target's own architecture and seed is the
    ORACLE drafter (every draft accepted) — the test lever that pins
    the accept-rate histogram's top bin.
    """

    def __init__(self, model, k: int):
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.model = model
        self.k = int(k)
        self._params = None
        self._pools = None
        self._draft_exec = None
        self._prefill_execs = {}
        self._started = False

    # -- lifecycle (inside the engine's warmup window) -----------------
    def start(self, engine) -> None:
        import jax
        import jax.numpy as jnp

        from ..core.executor import RNG_STATE_VAR

        cfg = engine.config
        scope = self.model.init_params()
        self._params = {
            n: jax.device_put(jnp.asarray(v))
            for n, v in scope.vars.items()
            if v is not None and n != RNG_STATE_VAR}
        self._pools = {n: jax.device_put(v) for n, v in
                       self.model.fresh_pools(cfg.num_pages,
                                              cfg.page_size).items()}
        params_spec = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                       for n, v in self._params.items()}
        pool_specs = self.model.pool_specs(cfg.num_pages,
                                           cfg.page_size)
        i32 = jnp.int32
        s = cfg.num_slots
        vec = jax.ShapeDtypeStruct((s,), i32)
        pt = jax.ShapeDtypeStruct((s, cfg.max_pages_per_slot), i32)
        donate = (5,) if engine._donate else ()
        self._draft_exec = jax.jit(
            self._build_draft_fn(),
            donate_argnums=donate).lower(
                params_spec, vec, vec, vec, pt, pool_specs).compile()
        # the full bucket ladder compiles here even on a decode-role
        # worker (the ENGINE skips its own prefill execs there; the
        # DRAFT pool still needs prompt KV on every import)
        for t in cfg.prefill_buckets:
            tok = jax.ShapeDtypeStruct((s, t), i32)
            last = jax.ShapeDtypeStruct((s, 1), i32)
            self._prefill_execs[t] = jax.jit(
                self._build_prefill_fn(t),
                donate_argnums=donate).lower(
                    params_spec, tok, vec, last, pt,
                    pool_specs).compile()
        self._started = True

    def _build_draft_fn(self):
        """k sequential draft steps as ONE jitted fori_loop: write the
        pending token's K/V, attend, argmax, advance — the engine's
        chunk loop shape with a static trip count (no early exit: a
        draft past the budget is capped by the engine, and its pool
        rows are overwritten before ever being read)."""
        import jax
        import jax.numpy as jnp

        from ..core.executor import interpret_program

        st = self.model.step
        program = st["main"]
        next_name = st["next_token"]
        cache_outs = st["cache_outs"]
        cache_names = self.model.cache_feed_names()
        fetches = (next_name, *cache_outs)
        k = self.k

        def draft_fn(params, tokens, write_pos, active, page_table,
                     pools):
            buf0 = jnp.zeros((tokens.shape[0], k), jnp.int32)

            def body(j, c):
                tok, wp, pls, buf = c
                env = dict(params)
                env.update(pls)
                env.update(tokens=tok, write_pos=wp, lengths=wp + 1,
                           active=active, page_table=page_table)
                env = interpret_program(program, env, None,
                                        fetch_names=fetches)
                nxt = env[next_name].astype(jnp.int32)
                new_pools = {n: env[o] for n, o in
                             zip(cache_names, cache_outs)}
                buf = buf.at[:, j].set(nxt)
                new_tok = jnp.where(active > 0, nxt, tok)
                return (new_tok, wp + active, new_pools, buf)

            _tok, _wp, pls, buf = jax.lax.fori_loop(
                0, k, body, (tokens, write_pos, pools, buf0))
            return buf, pls

        return draft_fn

    def _build_prefill_fn(self, t_bucket: int):
        import jax.numpy as jnp

        from ..core.executor import interpret_program

        pre = self.model.prefill(t_bucket)
        program = pre["main"]
        cache_outs = pre["cache_outs"]
        cache_names = self.model.cache_feed_names()

        def prefill_fn(params, tokens, seq_len, last_idx, page_table,
                       pools):
            env = dict(params)
            env.update(pools)
            env.update(tokens=tokens, seq_len=seq_len,
                       last_idx=last_idx, page_table=page_table)
            env = interpret_program(program, env, None,
                                    fetch_names=tuple(cache_outs))
            return {n: env[o]
                    for n, o in zip(cache_names, cache_outs)}

        return prefill_fn

    # -- engine hooks ---------------------------------------------------
    def on_prefill(self, engine, joiners, tokens, seq_len,
                   last_idx) -> None:
        """Mirror a prefill-on-join into the draft pool: the SAME
        padded host buffers the engine dispatched, addressed by the
        SAME page tables (geometry is shared by construction)."""
        import jax.numpy as jnp

        exec_ = self._prefill_execs[tokens.shape[1]]
        self._pools = exec_(
            self._params, jnp.asarray(tokens), jnp.asarray(seq_len),
            jnp.asarray(last_idx),
            jnp.asarray(engine._page_tables), self._pools)

    def on_import(self, engine, slot_id) -> None:
        """Disagg decode-role hook: a KV handoff seeded the TARGET
        slot but no draft-model KV crossed the wire — re-prefill the
        raw prompt into the draft pool locally (single joiner, every
        other slot masked out by seq_len 0)."""
        from .engine import BucketConfig

        slot = engine._slots[slot_id]
        prompt = np.asarray(slot.req.prompt)
        plen = int(prompt.size)
        bucket = BucketConfig.pick(engine.config.prefill_buckets, plen)
        if bucket is None:
            raise ValueError(
                f"draft-pool import re-prefill: prompt length {plen} "
                f"fits no prefill bucket "
                f"{list(engine.config.prefill_buckets)}")
        s = engine.config.num_slots
        tokens = np.zeros((s, bucket), np.int32)
        seq_len = np.zeros((s,), np.int32)
        last_idx = np.zeros((s, 1), np.int32)
        tokens[slot_id, :plen] = prompt
        seq_len[slot_id] = plen
        last_idx[slot_id, 0] = plen - 1
        self.on_prefill(engine, [slot_id], tokens, seq_len, last_idx)

    def draft(self, engine, active_ids):
        import jax.numpy as jnp

        s = engine.config.num_slots
        tokens = np.zeros((s,), np.int32)
        wp = np.zeros((s,), np.int32)
        act = np.zeros((s,), np.int32)
        draft_len = np.zeros((s,), np.int32)
        for i in active_ids:
            slot = engine._slots[i]
            tokens[i] = slot.cur_tok
            wp[i] = slot.committed
            act[i] = 1
            draft_len[i] = self.k
        buf, pools = self._draft_exec(
            self._params, jnp.asarray(tokens), jnp.asarray(wp),
            jnp.asarray(act), jnp.asarray(engine._page_tables),
            self._pools)
        self._pools = pools
        return np.asarray(buf), draft_len
