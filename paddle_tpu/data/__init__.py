"""Data plane: readers, decorators, datasets, DataFeeder.

reference: python/paddle/reader/decorator.py (shuffle/chain/compose/
buffered/firstn/map_readers/xmap_readers:58-338), python/paddle/dataset/
(auto-downloading datasets), python/paddle/fluid/data_feeder.py.
"""

from .data_feeder import DataFeeder  # noqa: F401
from .decorator import (Fake, batch, buffered, chain, compose, firstn,  # noqa: F401
                        map_readers, multiprocess_reader, shuffle,
                        xmap_readers)
from . import dataset  # noqa: F401
from . import image  # noqa: F401
