"""bf16 mixed-precision policy (paddle_tpu/amp.py).

Capability analog of the reference fp16 transpiler
(paddle/contrib/float16/float16_transpiler.py): white-list compute in
bf16, f32 master weights, f32 loss path.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(use_amp):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[16], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="int64")
        h = layers.fc(x, size=32, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if use_amp:
            opt = fluid.amp.decorate(opt)
        opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
    return main, startup, scope, loss, exe


def test_amp_marks_program():
    main, _, _, _, _ = _build(True)
    assert main._amp_lists is not None
    assert "mul" in main._amp_lists.white_list
    assert "softmax_with_cross_entropy" in main._amp_lists.black_list


def test_amp_trains_and_matches_f32():
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(32, 16).astype(np.float32),
            "y": rng.randint(0, 4, (32, 1)).astype(np.int64)}

    losses = {}
    for use_amp in (False, True):
        main, _, scope, loss, exe = _build(use_amp)
        with fluid.scope_guard(scope):
            vals = []
            for _ in range(20):
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                vals.append(float(np.asarray(lv).reshape(-1)[0]))
        losses[use_amp] = vals
    # both train; bf16 path stays close to f32 (bf16 has ~3 decimal
    # digits, so tolerance is loose but catches gross policy bugs)
    assert losses[True][-1] < losses[True][0]
    np.testing.assert_allclose(losses[True][0], losses[False][0],
                               rtol=0.05)
    np.testing.assert_allclose(losses[True][-1], losses[False][-1],
                               rtol=0.25, atol=0.05)


def test_amp_params_stay_f32():
    main, _, scope, loss, exe = _build(True)
    rng = np.random.RandomState(1)
    feed = {"x": rng.randn(8, 16).astype(np.float32),
            "y": rng.randint(0, 4, (8, 1)).astype(np.int64)}
    with fluid.scope_guard(scope):
        exe.run(main, feed=feed, fetch_list=[loss])
        for p in main.all_parameters():
            arr = scope.find_var(p.name)
            assert str(np.asarray(arr).dtype) == "float32", p.name


def test_amp_white_op_outputs_bf16():
    """A forward-only program: fc (mul) output must be bf16 under amp."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[8], dtype="float32")
        h = layers.fc(x, size=8, bias_attr=False)
        main._amp_lists = fluid.amp.AutoMixedPrecisionLists()
        exe = fluid.Executor()
        exe.run(startup)
        (hv,) = exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                        fetch_list=[h], return_numpy=False)
        assert str(hv.dtype) == "bfloat16"


def test_amp_survives_serialization():
    main, _, _, _, _ = _build(True)
    d = main.to_dict()
    assert d["amp"] is not None
    p2 = fluid.Program.from_dict(d)
    assert p2._amp_lists is not None
    assert p2._amp_lists.white_list == main._amp_lists.white_list
