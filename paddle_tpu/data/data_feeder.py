"""DataFeeder: python samples → feed dict of dense arrays.

reference: python/paddle/fluid/data_feeder.py — converts lists of sample
tuples to LoDTensors with lod construction.  Here ragged (lod_level=1)
slots are padded to the longest sequence in the batch (bucketed up to
`pad_to_multiple` to bound XLA retraces) and a `<name>.seq_len` int32
array carries the true lengths (SURVEY.md §5.7 segment-based design).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.program import Program, Variable


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None,
                 pad_to_multiple: int = 8):
        self.feed_vars: List[Variable] = []
        for v in feed_list:
            if isinstance(v, str):
                from ..core.program import default_main_program

                prog = program or default_main_program()
                v = prog.global_block().var(v)
            self.feed_vars.append(v)
        self.pad_to_multiple = pad_to_multiple

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        """iterable: list of sample tuples aligned with feed_list."""
        rows = list(iterable)
        if not rows:
            raise ValueError("empty batch")
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            column = [row[i] for row in rows]
            if var.lod_level > 0:
                padded, lens = self._pad(column, var)
                out[var.name] = padded
                out[f"{var.name}.seq_len"] = lens
            else:
                dtype = np.dtype(var.dtype)
                out[var.name] = np.asarray(column, dtype=dtype)
                want = var.shape
                got = out[var.name].shape
                if len(want) == len(got) + 1 and want[-1] == 1:
                    out[var.name] = out[var.name][..., None]
        return out

    def _pad(self, column, var):
        dtype = np.dtype(var.dtype)
        seqs = [np.asarray(s, dtype=dtype) for s in column]
        lens = np.asarray([len(s) for s in seqs], np.int32)
        max_len = int(lens.max())
        m = self.pad_to_multiple
        max_len = ((max_len + m - 1) // m) * m
        # fixed max length from the var shape wins (static-shape mode)
        if len(var.shape) >= 2 and var.shape[1] not in (-1, 0):
            max_len = var.shape[1]
        tail = seqs[0].shape[1:]
        padded = np.zeros((len(seqs), max_len) + tail, dtype=dtype)
        for i, s in enumerate(seqs):
            n = min(len(s), max_len)
            padded[i, :n] = s[:n]
        lens = np.minimum(lens, max_len)
        return padded, lens
