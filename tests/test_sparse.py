"""Sparse embedding path tests: SelectedRows-style grads, lazy optimizer
row updates, sharded tables on a mesh.

reference: paddle/fluid/operators/lookup_table_op.cc (SelectedRows grad),
math/selected_rows_functor.h (MergeAdd), optimizers/adam_op.h
(SparseAdamFunctor), distributed/parameter_prefetch.h (sharded table).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.selected_rows import SparseGrad

V, D, B, F = 50, 8, 16, 4


def _build(is_sparse, opt, vocab=V):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[B, F], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        emb = layers.embedding(
            ids, size=[vocab, D], is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(
                name="tbl", initializer=fluid.initializer.Constant(0.05)))
        s = layers.reduce_sum(emb, dim=1)
        p = layers.fc(s, size=1, param_attr=fluid.ParamAttr(
            name="w", initializer=fluid.initializer.Constant(0.2)))
        loss = layers.reduce_mean(layers.square_error_cost(p, y))
        {"sgd": lambda: fluid.optimizer.SGD(learning_rate=0.1),
         "adam": lambda: fluid.optimizer.Adam(learning_rate=0.01),
         "adagrad": lambda: fluid.optimizer.Adagrad(learning_rate=0.1),
         "momentum": lambda: fluid.optimizer.Momentum(
             learning_rate=0.1, momentum=0.9)}[opt]().minimize(loss)
    return main, startup, loss


def _train(main, startup, loss, feed, steps=5):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        losses = [float(exe.run(main, feed=feed,
                                fetch_list=[loss])[0].reshape(()))
                  for _ in range(steps)]
        table = np.asarray(scope.find_var("tbl"))
    return losses, table


@pytest.fixture()
def feed():
    rng = np.random.RandomState(0)
    return {"ids": rng.randint(0, V, (B, F)).astype(np.int64),
            "y": rng.rand(B, 1).astype(np.float32)}


@pytest.mark.parametrize("opt", ["sgd", "adagrad", "momentum"])
def test_sparse_matches_dense_trajectory(opt, feed):
    """Exact-parity optimizers: the sparse (rows+ids) path must reproduce
    the dense scatter-add trajectory bit-for-bit-ish."""
    ref_losses, ref_tbl = _train(*_build(False, opt), feed)
    sp_losses, sp_tbl = _train(*_build(True, opt), feed)
    np.testing.assert_allclose(sp_losses, ref_losses, rtol=1e-5)
    np.testing.assert_allclose(sp_tbl, ref_tbl, rtol=1e-5, atol=1e-7)


def test_sparse_adam_is_lazy(feed):
    """Sparse Adam updates only touched rows (reference SparseAdamFunctor
    lazy semantics): untouched rows must stay exactly at init, while the
    dense path moves every row (bias-corrected m/v are 0/0 but the
    update is still applied globally once any grad step ran)."""
    losses, tbl = _train(*_build(True, "adam"), feed)
    assert losses[-1] < losses[0]
    touched = np.unique(feed["ids"])
    untouched = np.setdiff1d(np.arange(V), touched)
    assert untouched.size > 0
    init = np.float32(0.05)
    np.testing.assert_array_equal(tbl[untouched], np.full_like(
        tbl[untouched], init))
    assert not np.allclose(tbl[touched], init)


def test_sparse_grad_merged_dedups():
    import jax.numpy as jnp

    ids = jnp.asarray([3, 1, 3, 7, 1, 3], jnp.int32)
    rows = jnp.arange(6 * 2, dtype=jnp.float32).reshape(6, 2)
    g = SparseGrad(ids, rows, (10, 2))
    valid, mids, mrows = g.merged()
    valid = np.asarray(valid)
    mids = np.asarray(mids)[valid]
    mrows = np.asarray(mrows)[valid]
    assert sorted(mids.tolist()) == [1, 3, 7]
    ref = {1: rows[1] + rows[4], 3: rows[0] + rows[2] + rows[5],
           7: rows[3]}
    for i, r in zip(mids, mrows):
        np.testing.assert_allclose(r, np.asarray(ref[int(i)]))
    # to_dense equals plain scatter-add
    dense = np.zeros((10, 2), np.float32)
    np.add.at(dense, np.asarray(ids), np.asarray(rows))
    np.testing.assert_allclose(np.asarray(g.to_dense()), dense)


def test_sparse_respects_padding_idx(feed):
    """The padding row must stay frozen on the sparse path exactly as on
    the dense path (the cotangent at padding positions must be zeroed)."""
    results = {}
    for is_sparse in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[B, F], dtype="int64",
                              append_batch_size=False)
            y = layers.data("y", shape=[B, 1], append_batch_size=False)
            emb = layers.embedding(
                ids, size=[V, D], is_sparse=is_sparse, padding_idx=0,
                param_attr=fluid.ParamAttr(
                    name="tbl",
                    initializer=fluid.initializer.Constant(0.05)))
            s = layers.reduce_sum(emb, dim=1)
            p = layers.fc(s, size=1, param_attr=fluid.ParamAttr(
                name="w", initializer=fluid.initializer.Constant(0.2)))
            loss = layers.reduce_mean(layers.square_error_cost(p, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
            results[is_sparse] = np.asarray(scope.find_var("tbl"))
    np.testing.assert_array_equal(results[True][0],
                                  np.full(D, np.float32(0.05)))
    np.testing.assert_allclose(results[True], results[False],
                               rtol=1e-5, atol=1e-7)


def test_sparse_falls_back_when_table_shared(feed):
    """A table consumed by a non-lookup op must take the dense path (the
    sparse grad would silently miss the other consumer's contribution)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[B, F], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        emb = layers.embedding(
            ids, size=[V, D], is_sparse=True,
            param_attr=fluid.ParamAttr(
                name="tbl", initializer=fluid.initializer.Constant(0.05)))
        s = layers.reduce_sum(emb, dim=1)
        # second consumer of the table: a pooled regularizer-ish term
        tbl_var = main.global_block().var("tbl")
        reg = layers.reduce_mean(layers.square(tbl_var))
        p = layers.fc(s, size=1)
        loss = layers.elementwise_add(
            layers.reduce_mean(layers.square_error_cost(p, y)),
            layers.scale(reg, scale=10.0))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        tbl = np.asarray(scope.find_var("tbl"))
    # the reg term's gradient reaches every row — including untouched ids
    untouched = np.setdiff1d(np.arange(V), np.unique(feed["ids"]))
    assert not np.allclose(tbl[untouched], 0.05), \
        "dense fallback missing: untouched rows ignored the shared term"


def test_sharded_table_matches_single_device(feed):
    """Table sharded over the 'mp' axis (vocab dim) under GSPMD produces
    the same training trajectory as the unsharded single-device run —
    the distributed-lookup-table capability via collectives
    (reference: distributed/parameter_prefetch.h id-sharded gather)."""
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.strategies import ShardingRules

    # vocab divisible by mesh axis for clean sharding
    vocab = 48
    feed = dict(feed)
    feed["ids"] = np.clip(feed["ids"], 0, vocab - 1)

    ref_losses, ref_tbl = _train(*_build(False, "sgd", vocab=vocab), feed)

    main, startup, loss = _build(True, "sgd", vocab=vocab)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        bs = fluid.BuildStrategy()
        bs.sharding_rules = ShardingRules(rules=[(r"^tbl$", ("mp", None))])
        compiled = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs,
            mesh=make_mesh({"dp": 2, "mp": 4}))
        losses = [float(exe.run(compiled, feed=feed,
                                fetch_list=[loss])[0].reshape(()))
                  for _ in range(5)]
        tbl = np.asarray(scope.find_var("tbl"))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    np.testing.assert_allclose(tbl, ref_tbl, rtol=1e-4, atol=1e-6)


def test_deepfm_sparse_trains():
    from paddle_tpu.models import deepfm

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = deepfm.build_model(vocab_size=10001, dnn_hidden=(64, 64))
        exe = fluid.Executor()
        exe.run(startup)
        feed = deepfm.make_fake_batch(64, vocab_size=10001)
        losses = [
            float(exe.run(main, feed=feed,
                          fetch_list=[model["loss"]])[0].reshape(()))
            for _ in range(8)
        ]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
