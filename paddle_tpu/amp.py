"""Automatic mixed precision (bf16) training.

Capability analog of the reference fp16 path
(reference: paddle/contrib/float16/float16_transpiler.py — a program
rewrite inserting cast ops; python/paddle/fluid/contrib was growing the
same op-list policy).  TPU-native design: instead of rewriting the
program with cast ops, the Executor applies a dtype policy at op dispatch
inside the single jit trace — white-list ops (MXU matmul/conv families)
consume bfloat16, black-list ops (softmax/loss/reductions) are forced to
float32, everything else runs in whichever dtype arrives.  Parameters
stay float32 master copies: the cast happens at the op boundary, so
jax AD accumulates gradients in float32 and optimizer updates are full
precision.  bf16 has the dynamic range of f32, so no loss scaling is
needed (the fp16 transpiler's scale machinery is unnecessary on TPU).

Usage (fluid style)::

    opt = fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    opt = fluid.amp.decorate(opt)      # returns wrapped optimizer
    opt.minimize(avg_cost)             # marks the program as amp
"""

from __future__ import annotations

from typing import Optional, Set

# Ops whose FLOPs dominate and map onto the MXU: run in bf16.
DEFAULT_WHITE: Set[str] = {
    "mul", "matmul", "conv2d", "conv3d", "depthwise_conv2d",
    "conv2d_transpose", "conv3d_transpose", "flash_attention",
    "sequence_conv",
}

# Numerically sensitive ops: force f32 inputs.
DEFAULT_BLACK: Set[str] = {
    "softmax", "softmax_with_cross_entropy", "cross_entropy",
    "sigmoid_cross_entropy_with_logits", "mean", "reduce_mean",
    "reduce_sum", "sum", "exp", "log", "cos_sim", "kldiv_loss",
}


class AutoMixedPrecisionLists:
    """White/black op-type lists with user overrides (mirrors the list
    policy the reference fp16 transpiler hardcoded)."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(DEFAULT_WHITE) | set(custom_white_list or ())
        self.black_list = set(DEFAULT_BLACK) | set(custom_black_list or ())
        overlap = self.white_list & self.black_list
        if overlap:
            raise ValueError(
                f"ops in both white and black amp lists: {sorted(overlap)}")


class OptimizerWithMixedPrecision:
    """Optimizer wrapper: marks the program as amp at minimize() time.

    The wrapped optimizer is unchanged — master weights are the normal
    f32 params, so every optimizer composes with amp.  With
    `loss_scaling` set (a resilience.LossScaleConfig), minimize() also
    enables the in-step non-finite update guard with dynamic loss
    scaling (resilience/guard.py) — the fp16 transpiler's scale
    machinery, TPU-native.
    """

    def __init__(self, optimizer,
                 amp_lists: Optional[AutoMixedPrecisionLists],
                 loss_scaling=None):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = loss_scaling

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        program._amp_lists = self._amp_lists
        program._bump()
        result = self._optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        if self._loss_scaling is not None:
            # after minimize: the guard must see the full op list
            # (backward marker + update ops are appended by now)
            from .resilience.guard import enable_update_guard

            enable_update_guard(program, loss_scaling=self._loss_scaling)
        return result


def decorate(optimizer, amp_lists: Optional[AutoMixedPrecisionLists] = None,
             use_dynamic_loss_scaling: bool = False,
             init_loss_scaling: float = 2.0 ** 15,
             incr_every_n_steps: int = 1000,
             decr_every_n_nan_or_inf: int = 1,
             incr_ratio: float = 2.0, decr_ratio: float = 0.5):
    """Wrap `optimizer` for bf16 mixed-precision training.

    use_dynamic_loss_scaling: enable the device-side loss-scale
        schedule + non-finite update guard (reference: fluid's
        decorate(init_loss_scaling=..., use_dynamic_loss_scaling=True)
        fp16 API).  bf16 usually needs no scaling (f32 dynamic range) —
        this is the fp16/overflow-hardening opt-in; the update guard it
        brings protects bf16 runs from NaN steps too.
    """
    loss_scaling = None
    if use_dynamic_loss_scaling:
        from .resilience.guard import LossScaleConfig

        loss_scaling = LossScaleConfig(
            init_loss_scaling=init_loss_scaling,
            incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio)
    return OptimizerWithMixedPrecision(optimizer, amp_lists,
                                       loss_scaling=loss_scaling)


def cast_ins_for_op(op_type: str, ins, amp_lists: AutoMixedPrecisionLists):
    """Apply the dtype policy to one op's input slots (called from the
    executor's trace loop)."""
    import jax.numpy as jnp

    if op_type in amp_lists.white_list:
        src, dst = jnp.float32, jnp.bfloat16
    elif op_type in amp_lists.black_list:
        src, dst = jnp.bfloat16, jnp.float32
    else:
        return ins

    def cast(v):
        if hasattr(v, "dtype") and v.dtype == src:
            return v.astype(dst)
        return v

    return {slot: [cast(v) for v in vals] for slot, vals in ins.items()}
