"""Observe pillar 6: numerics observability — per-layer training
dynamics and first-nonfinite op provenance, all device-side.

The reference ran a per-op NaN scan on HOST after every op
(operator.cc:943 under FLAGS_check_nan_inf) — affordable on a
stream-per-op runtime, a per-step device->host sync here.  This module
is the production replacement, built entirely under the
one-jitted-step invariant (CLAUDE.md: no host round-trips, no
callbacks — tunnel-safe).  Two capabilities:

1. PER-LAYER TRAINING DYNAMICS — grad norm, param norm and update
   ratio (|dw|/|w|) accumulated per NAMED PARAMETER GROUP.  Groups are
   the sharding-layer names (`parallel/strategies.py` keys the
   Megatron rules on exactly these): attn_qkv / attn_out / ffn_in /
   ffn_out / moe_gate / moe_expert / embedding / other.  The group
   vocabulary is FIXED and bounded so the telemetry carry stays a few
   (G,) vectors riding the existing `__telemetry__` accumulator —
   through `chain_iterations`' fori_loop and the same periodic
   `fetch_telemetry` sync.  This is what dead-layer detection
   (update_ratio ~ 0 while |w| > 0) and explosion attribution (which
   layer's grad norm blew up) read.

2. FIRST-NONFINITE OP PROVENANCE — each step computes a packed per-op
   finite bitmap (one bit per fluid op, 32 bits per word, keyed by the
   op's block index) from the op's outputs, in-trace.  The bitmap is
   LATCHED into the accumulator on the first poisoned step of a
   window; subsequent clean (or later-poisoned) steps never overwrite
   it.  Host-side, `join_first_nonfinite` joins the latched bit back
   to the fluid op type/name/group via the program desc, so a guard
   trip reads "op 143 `softmax_with_cross_entropy` (loss head) first
   produced nonfinite" instead of a bare counter.

Scope notes (documented limits, all loud in docs/OBSERVE.md):
- ops inside control-flow SUB-BLOCKS attribute to the macro op that
  owns them (the while/cond op's own bit), not to block-local indices;
- the backward (autodiff) region is not a fluid op: a step whose op
  outputs are all finite but whose grads are not latches with ZERO
  bits and reports origin "backward/autodiff";
- provenance applies to training programs (the step with a backward
  boundary) — inference nonfinites surface via FLAGS.check_nan_inf.

Enabling is a program-level flag (`enable_numerics`) exactly like
`enable_telemetry`, and bumps the program version so cached unguarded
step fns are not reused.  Disabled, every hook is a dict-membership
check at TRACE time — the lowered step is byte-identical
(tests/test_observe_numerics.py asserts the runtime_stats discipline).
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

# Per-step, trace-local bitmap riding `env` (NEVER part of the donated
# state: it is re-zeroed at the top of every step and folded into the
# telemetry accumulator's latch at the bottom).
NUMERICS_BITS_VAR = "__numerics_bits__"

# Latched-bitmap fields inside the `__telemetry__` accumulator.
NONFINITE_WORDS = "nonfinite_op_words"
NONFINITE_LATCH = "nonfinite_latched"

# The bounded group vocabulary — ordered, first match wins.  These are
# the NAMED transformer-layer prefixes the sharding rules key on
# (parallel/strategies.py); `switch_moe(name=...)` APPENDS user names
# to the moe_gate/moe_expert prefixes, and LayerHelper prefixes every
# generated param/tmp name with the layer name, so an un-anchored
# substring search is the stable match.
GROUP_NAMES = ("attn_qkv", "attn_out", "ffn_in", "ffn_out",
               "moe_gate", "moe_expert", "embedding", "other")
N_GROUPS = len(GROUP_NAMES)

_GROUP_PATTERNS = [
    ("attn_qkv", re.compile(r"attn_qkv")),
    ("attn_out", re.compile(r"attn_out")),
    ("ffn_in", re.compile(r"ffn_in")),
    ("ffn_out", re.compile(r"ffn_out")),
    ("moe_gate", re.compile(r"moe_gate")),
    ("moe_expert", re.compile(r"moe_expert")),
    # word_emb / src_word_emb / word_embedding / fm_emb / pos_enc emb
    ("embedding", re.compile(r"emb")),
]

# per-group window fields (all (G,) float32 vectors; squared norms so
# cross-group sums compose exactly: sum_g group_gsq == global gnorm^2)
GROUP_FIELDS = ("group_gsq_last", "group_gsq_sum", "group_usq_last",
                "group_usq_sum", "group_psq_last")


def group_of(name: str) -> int:
    """Group index for one parameter/variable name (first pattern that
    matches anywhere in the name wins; unmatched -> other)."""
    for i, (_g, pat) in enumerate(_GROUP_PATTERNS):
        if pat.search(name):
            return i
    return N_GROUPS - 1  # "other"


def param_groups(names: Iterable[str]) -> Dict[str, int]:
    """name -> group index for a parameter set (host-side, trace
    setup)."""
    return {n: group_of(n) for n in names}


# ---------------------------------------------------------------------------
# Program-level switch (mirrors metrics.enable_telemetry)
# ---------------------------------------------------------------------------

def enable_numerics(program) -> None:
    """Opt a Program's compiled step into numerics observability
    (per-group dynamics + first-nonfinite provenance).  Implies
    device-side telemetry; bumps the program version so an
    already-cached step fn without the numerics carry is not reused."""
    from . import metrics as _metrics

    program._numerics_enabled = True
    _metrics.enable_telemetry(program)
    program._bump()


def numerics_enabled(program) -> bool:
    return bool(getattr(program, "_numerics_enabled", False))


# ---------------------------------------------------------------------------
# Accumulator fields (host init; live on device from the first step)
# ---------------------------------------------------------------------------

def n_bit_words(n_ops: int) -> int:
    return max(1, int(math.ceil(n_ops / 32.0)))


def init_numerics_fields(n_ops: int) -> Dict[str, Any]:
    """Zeroed numerics fields merged into init_telemetry()'s dict when
    the program opted in (metrics.init_telemetry_for)."""
    out: Dict[str, Any] = {
        f: np.zeros(N_GROUPS, np.float32) for f in GROUP_FIELDS}
    out[NONFINITE_WORDS] = np.zeros(n_bit_words(n_ops), np.uint32)
    out[NONFINITE_LATCH] = np.int32(0)
    return out


# ---------------------------------------------------------------------------
# Trace-time helpers (called from core/executor.py inside the jit)
# ---------------------------------------------------------------------------

def init_step_bits(n_ops: int):
    """Fresh all-finite bitmap for one step (trace-time zeros)."""
    import jax.numpy as jnp

    return jnp.zeros(n_bit_words(n_ops), jnp.uint32)


def _float_parts(values):
    """Float array leaves of a list of op outputs: SparseGrad
    contributes rows, tensor-array tuples and host constants are
    skipped, non-float dtypes are always finite."""
    import jax.numpy as jnp

    from ..core.selected_rows import SparseGrad

    for v in values:
        if isinstance(v, SparseGrad):
            v = v.rows
        if isinstance(v, (tuple, list)) or not hasattr(v, "dtype") \
                or not hasattr(v, "ndim"):
            continue
        try:
            if jnp.issubdtype(v.dtype, jnp.floating):
                yield v
        except TypeError:
            continue


def update_bits(bits, op_index: int, values):
    """OR op `op_index`'s nonfinite flag into the step bitmap (pure
    jnp; one isfinite-all reduction per float output)."""
    import jax.numpy as jnp

    bad = None
    for a in _float_parts(values):
        b = ~jnp.all(jnp.isfinite(a.astype(jnp.float32)))
        bad = b if bad is None else (bad | b)
    if bad is None:
        return bits
    word, bit = divmod(int(op_index), 32)
    if word >= bits.shape[0]:  # defensive: op beyond the built bitmap
        return bits
    return bits.at[word].set(
        bits[word] | (bad.astype(jnp.uint32) << jnp.uint32(bit)))


def or_across_axis(words, axis_name: str):
    """Exact bitwise-OR all-reduce of a bitmap over a shard_map axis
    (the explicit grad-sync path): per-bit pmax — a plain pmax over
    packed words would keep one rank's word, losing bits another rank
    set in the same word."""
    import jax
    import jax.numpy as jnp

    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((words[:, None] >> shifts) & jnp.uint32(1)).astype(jnp.int32)
    bits = jax.lax.pmax(bits, axis_name)
    return jnp.sum(bits.astype(jnp.uint32) << shifts, axis=1,
                   dtype=jnp.uint32)


def device_group_update(tel: Dict[str, Any], grads: Dict[str, Any],
                        params_before: Dict[str, Any],
                        env: Dict[str, Any],
                        groups: Dict[str, int]) -> Dict[str, Any]:
    """One step's per-group accumulation (pure jnp, inside the trace).
    Mirrors metrics.device_update's global norms but scatter-adds each
    parameter's squared norm into its group slot, so
    sum_g group_gsq_last == grad_norm_last^2 exactly (fp order aside).
    params_before are the PRE-update values (the |w| denominator of the
    update ratio); env holds the post-update values."""
    import jax.numpy as jnp

    from ..core.selected_rows import SparseGrad

    gsq = jnp.zeros(N_GROUPS, jnp.float32)
    psq = jnp.zeros(N_GROUPS, jnp.float32)
    usq = jnp.zeros(N_GROUPS, jnp.float32)
    for pname, g in grads.items():
        idx = groups.get(pname, N_GROUPS - 1)
        parts = (g.rows,) if isinstance(g, SparseGrad) else (g,)
        for a in parts:
            af = a.astype(jnp.float32)
            gsq = gsq.at[idx].add(jnp.sum(af * af))
    for pname, old in params_before.items():
        idx = groups.get(pname, N_GROUPS - 1)
        of = old.astype(jnp.float32)
        psq = psq.at[idx].add(jnp.sum(of * of))
        new = env.get(pname)
        if new is None or new is old:
            continue
        d = new.astype(jnp.float32) - of
        usq = usq.at[idx].add(jnp.sum(d * d))
    out = dict(tel)
    out.update({
        "group_gsq_last": gsq,
        "group_gsq_sum": tel["group_gsq_sum"] + gsq,
        "group_usq_last": usq,
        "group_usq_sum": tel["group_usq_sum"] + usq,
        "group_psq_last": psq,
    })
    return out


def latch_step_bits(tel: Dict[str, Any], bits,
                    poisoned_extra=None) -> Dict[str, Any]:
    """Latch the step bitmap into the accumulator: the FIRST poisoned
    step of a window wins; clean steps never clear it and later
    poisoned steps never overwrite it.  `poisoned_extra` (optional
    traced bool, e.g. ~all_finite from the update guard) latches a
    backward-origin nonfinite even when every op output was finite —
    with zero bits, which the host join reports as backward/autodiff."""
    import jax.numpy as jnp

    poisoned = jnp.any(bits != 0)
    if poisoned_extra is not None:
        poisoned = poisoned | poisoned_extra
    latched = tel[NONFINITE_LATCH] > 0
    out = dict(tel)
    # when not yet latched the stored words are all-zero, so taking
    # `bits` unconditionally on the not-latched branch is exact for
    # clean steps too (bits == 0 == stored)
    out[NONFINITE_WORDS] = jnp.where(latched, tel[NONFINITE_WORDS], bits)
    out[NONFINITE_LATCH] = (latched | poisoned).astype(jnp.int32)
    return out


# ---------------------------------------------------------------------------
# Host-side joins (the periodic fetch / reports)
# ---------------------------------------------------------------------------

def join_first_nonfinite(words, program=None) -> Optional[Dict[str, Any]]:
    """Join a latched bitmap back to the fluid op: lowest set bit ->
    {op_index, op_type, group, outputs}.  With no program the index
    stands alone; with zero bits (backward-origin latch) the origin is
    named explicitly."""
    arr = np.asarray(words)
    idx = None
    for w in range(arr.shape[0]):
        word = int(arr[w])
        if word:
            idx = w * 32 + ((word & -word).bit_length() - 1)
            break
    if idx is None:
        return {"op_index": None, "op_type": "backward/autodiff",
                "group": None,
                "note": "all op outputs finite; nonfinite arose in "
                        "the gradient computation"}
    info: Dict[str, Any] = {"op_index": idx}
    if program is not None:
        ops = program.global_block().ops
        if idx < len(ops):
            desc = ops[idx].desc
            outs = desc.output_names()
            info["op_type"] = desc.type
            info["outputs"] = outs[:4]
            info["group"] = (GROUP_NAMES[group_of(outs[0])] if outs
                             else None)
    return info


def summarize_groups(host: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """Per-group window summary from fetched (host) accumulator
    fields.  Groups with no parameters (all-zero everywhere) are
    omitted; `grad_norm_rms`/`update_ratio_rms` are RMS-over-steps of
    the per-step norms (sqrt of the mean squared norm)."""
    n = max(int(host.get("steps", 0)), 1)
    gsql = np.asarray(host["group_gsq_last"], np.float64)
    gsqs = np.asarray(host["group_gsq_sum"], np.float64)
    usql = np.asarray(host["group_usq_last"], np.float64)
    usqs = np.asarray(host["group_usq_sum"], np.float64)
    psql = np.asarray(host["group_psq_last"], np.float64)
    out: Dict[str, Dict[str, float]] = {}
    for i, gname in enumerate(GROUP_NAMES):
        if not (gsql[i] or gsqs[i] or usql[i] or usqs[i] or psql[i]):
            continue  # no parameters in this group
        pn = float(np.sqrt(psql[i]))
        un = float(np.sqrt(usql[i]))
        out[gname] = {
            "grad_norm_last": float(np.sqrt(gsql[i])),
            "grad_norm_rms": float(np.sqrt(gsqs[i] / n)),
            "param_norm": pn,
            "update_norm_last": un,
            "update_ratio": (un / pn) if pn > 0 else 0.0,
            "update_ratio_rms": (float(np.sqrt(usqs[i] / n)) / pn)
            if pn > 0 else 0.0,
        }
    return out


def worst_update_ratio(groups: Optional[Dict[str, Dict[str, float]]]):
    """(group_name, ratio) with the LARGEST update ratio (explosion
    attribution), or (None, None) when no groups reported."""
    if not groups:
        return None, None
    name = max(groups, key=lambda g: groups[g]["update_ratio"])
    return name, groups[name]["update_ratio"]


# update ratio below this while |w| > 0 flags a group as dead (no
# optimizer movement at all — e.g. a detached layer or a zero lr)
DEAD_RATIO = 1e-10


def numerics_report(tel) -> Dict[str, Any]:
    """Structured numerics health report from one fetched
    StepTelemetry window: per-group dynamics, dead-layer flags,
    explosion attribution, and the first-nonfinite provenance."""
    groups = getattr(tel, "groups", None) or {}
    dead = sorted(g for g, s in groups.items()
                  if s["param_norm"] > 0
                  and s["update_ratio"] < DEAD_RATIO)
    wname, wratio = worst_update_ratio(groups)
    return {
        "steps": tel.steps,
        "healthy": tel.healthy,
        "groups": groups,
        "dead_groups": dead,
        "worst_update_ratio_group": wname,
        "worst_update_ratio": wratio,
        "first_nonfinite_op": getattr(tel, "first_nonfinite_op", None),
        "nonfinite_grad_steps": tel.nonfinite_grad_steps,
        "skipped_update_steps": tel.skipped_update_steps,
    }


def format_numerics_table(tel) -> str:
    """The report as an aligned text table (the observe pillar-6 analog
    of format_memory_table/format_cost_table)."""
    rep = numerics_report(tel)
    lines: List[str] = []
    lines.append(f"{'group':<12} {'grad_norm':>12} {'param_norm':>12} "
                 f"{'upd_ratio':>11}  flags")
    for gname in GROUP_NAMES:
        s = rep["groups"].get(gname)
        if s is None:
            continue
        flags = "DEAD" if gname in rep["dead_groups"] else ""
        if gname == rep["worst_update_ratio_group"]:
            flags = (flags + " worst").strip()
        lines.append(f"{gname:<12} {s['grad_norm_last']:>12.4e} "
                     f"{s['param_norm']:>12.4e} "
                     f"{s['update_ratio']:>11.3e}  {flags}")
    fno = rep["first_nonfinite_op"]
    if fno is not None:
        where = (f"op {fno.get('op_index')} "
                 f"{fno.get('op_type', '?')!r}"
                 + (f" (group {fno['group']})" if fno.get("group")
                    else ""))
        lines.append(f"first nonfinite: {where}")
    lines.append(f"steps={rep['steps']} healthy={rep['healthy']} "
                 f"nonfinite_grad_steps={rep['nonfinite_grad_steps']} "
                 f"skipped_update_steps={rep['skipped_update_steps']}")
    return "\n".join(lines)
