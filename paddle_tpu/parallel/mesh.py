"""Device mesh construction.

Replaces the reference's device topology handling (NCCLContextMap over
places, platform/nccl_helper.h:86; multi-trainer ranks at
parallel_executor.cc:254).  A Mesh names the parallelism axes; shardings
reference axes by name and XLA routes collectives over ICI (fast, within
slice) vs DCN (across slices) according to mesh layout.

Conventional axis names: "dp" (data), "mp" (tensor/model), "sp"
(sequence/context), "pp" (pipeline), "ep" (expert).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


def make_mesh(axes: Dict[str, int], devices=None):
    """Build a jax.sharding.Mesh with named axes, e.g.
    make_mesh({"dp": 4, "mp": 2})."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = int(np.prod(list(axes.values())))
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices, only {len(devices)} available")
    arr = np.asarray(devices[:n]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


_default_mesh = None
_executing_mesh = None


def set_default_mesh(mesh):
    global _default_mesh
    _default_mesh = mesh


class executing_mesh:
    """Trace-time marker: the mesh a CompiledProgram is being traced
    under.  Mesh-aware op impls (sequence-parallel flash attention)
    read it via get_executing_mesh() to route onto shard_map
    collectives; it is set only while the wrapper traces its step."""

    def __init__(self, mesh):
        self._mesh = mesh

    def __enter__(self):
        global _executing_mesh
        self._prev = _executing_mesh
        _executing_mesh = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        global _executing_mesh
        _executing_mesh = self._prev
        return False


def get_executing_mesh():
    return _executing_mesh


def get_default_mesh(create_dp: bool = True):
    """The process-wide mesh; lazily a pure-DP mesh over all devices."""
    global _default_mesh
    if _default_mesh is None and create_dp:
        import jax

        _default_mesh = make_mesh({"dp": len(jax.devices())})
    return _default_mesh
