"""Fused attention op.

The reference composes attention from matmul/softmax primitives
(nets.py scaled_dot_product_attention; the 2018 codebase has no fused
kernel — SURVEY.md §5.7 marks this a capability gap to fill natively).
`flash_attention` is the single-op attention: inputs Q/K/V laid out
(N, H, T, D) plus an optional additive Bias; the default implementation
is a numerically-stable lax composition (XLA fuses it well on TPU), and
ops/pallas/flash_attention.py provides the tiled Pallas kernel used when
`use_pallas` is set and we're on TPU (forward via custom_vjp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, opt_in, out


def _xla_attention(q, k, v, bias, scale, causal):
    logits = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), jnp.bool_))
        logits = jnp.where(mask, logits, -1e9)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    o = jnp.einsum("nhqk,nhkd->nhqd", weights.astype(q.dtype), v)
    return o


@register_op("flash_attention")
def flash_attention(ctx, ins, attrs):
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    bias = opt_in(ins, "Bias")
    scale = attrs.get("scale", None)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    causal = attrs.get("causal", False)
    if attrs.get("use_pallas", False):
        from .pallas.flash_attention import pallas_flash_attention

        o = pallas_flash_attention(q, k, v, bias, scale, causal)
    else:
        o = _xla_attention(q, k, v, bias, scale, causal)
    return out(Out=o)
