"""StepTelemetry: device-side training-health accumulator.

The architecture invariant (CLAUDE.md) is that a training step is ONE
jitted XLA computation with no host round-trips, and the tunnel backend
supports no host callbacks — so per-step scalars (loss, grad norm,
update norm, non-finite counts) must ACCUMULATE ON DEVICE as extra
carry state of the jitted step and be fetched every N steps in one
host sync ("device-accumulate, periodic-fetch").  The accumulator is a
flat dict-of-scalars pytree living in the executor state under
`TELEMETRY_VAR`; `core/executor.py` threads it through the step (and
through `chain_iterations`' fori_loop carry, so K chained iterations
accumulate K updates with zero extra dispatches).

reference analog: the reference's per-op NaN scan ran on HOST after
every op (operator.cc:943 FLAGS_check_nan_inf) — affordable on a
stream-per-op runtime, a per-step device->host sync here.  The
host-side `_debug_checks` path still exists for debugging; this module
is the production-telemetry replacement that costs one fetch per
window.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

TELEMETRY_VAR = "__telemetry__"

_F32_FIELDS = ("loss_sum", "loss_last", "grad_norm_sum", "grad_norm_last",
               "update_norm_sum", "update_norm_last")
_I32_FIELDS = ("steps", "nonfinite_grad_steps", "nonfinite_loss_steps",
               "skipped_update_steps")
# update-guard state (resilience/guard.py) rides the same accumulator
# but is NOT a window counter: a telemetry reset must preserve it, or
# the loss-scale schedule would restart every fetch
_PERSISTENT_FIELDS = ("loss_scale", "ls_good_steps", "ls_bad_steps")


def enable_telemetry(program) -> None:
    """Opt a Program's compiled step into device-side telemetry.  Must
    be set before the Executor builds/caches the step fn for this
    (program, feeds, fetches) combination — enabling later changes the
    cache key, forcing a rebuild, so it still takes effect (at one
    retrace's cost)."""
    program._telemetry_enabled = True


def telemetry_enabled(program) -> bool:
    return bool(getattr(program, "_telemetry_enabled", False))


def init_telemetry(loss_scale: float = 1.0) -> Dict[str, Any]:
    """Fresh zeroed accumulator (host values; become device arrays on
    first dispatch).  `loss_scale` seeds the dynamic loss-scale scalar
    (resilience update guard); 1.0 = inert."""
    out: Dict[str, Any] = {f: np.float32(0.0) for f in _F32_FIELDS}
    out.update({f: np.int32(0) for f in _I32_FIELDS})
    out["loss_scale"] = np.float32(loss_scale)
    out["ls_good_steps"] = np.int32(0)
    out["ls_bad_steps"] = np.int32(0)
    return out


def init_telemetry_for(program) -> Dict[str, Any]:
    """Accumulator sized for one program: guard loss-scale seed plus,
    when the program opted into numerics observability
    (observe.numerics), the per-group vectors and the latched
    first-nonfinite bitmap (one bit per fluid op)."""
    guard_cfg = getattr(program, "_update_guard", None)
    out = init_telemetry(loss_scale=guard_cfg.init_loss_scale
                         if guard_cfg is not None else 1.0)
    if getattr(program, "_numerics_enabled", False):
        from . import numerics as _numerics

        out.update(_numerics.init_numerics_fields(
            len(program.global_block().ops)))
    return out


def ensure_numerics_fields(program, tel: Dict[str, Any]) -> Dict[str, Any]:
    """Patch an EXISTING scope accumulator when numerics was enabled
    after telemetry already ran (or the program grew ops): merge in
    correctly-sized zeroed numerics fields, preserving every window
    counter and the guard's loss-scale schedule.  Returns `tel`
    unchanged when nothing is missing."""
    if not getattr(program, "_numerics_enabled", False):
        return tel
    from . import numerics as _numerics

    n_ops = len(program.global_block().ops)
    words = tel.get(_numerics.NONFINITE_WORDS)
    if words is not None and \
            np.asarray(words).shape[0] == _numerics.n_bit_words(n_ops):
        return tel
    out = dict(tel)
    out.update(_numerics.init_numerics_fields(n_ops))
    return out


def device_update(tel: Dict[str, Any], loss, grads: Dict[str, Any],
                  params_before: Dict[str, Any],
                  env: Dict[str, Any]) -> Dict[str, Any]:
    """One step's accumulation — runs INSIDE the jit trace (pure, no
    callbacks).  grads may contain SparseGrad pytrees (their touched
    rows carry the whole gradient mass, so the norm over rows is the
    true table-grad norm up to duplicate-id merging)."""
    import jax.numpy as jnp

    from ..core.selected_rows import SparseGrad

    gsq = jnp.float32(0.0)
    nonfinite = jnp.int32(0)
    for g in grads.values():
        parts = (g.rows,) if isinstance(g, SparseGrad) else (g,)
        for a in parts:
            af = a.astype(jnp.float32)
            gsq = gsq + jnp.sum(af * af)
            nonfinite = nonfinite + (~jnp.isfinite(af)).sum().astype(
                jnp.int32)
    usq = jnp.float32(0.0)
    for pname, old in params_before.items():
        new = env.get(pname)
        if new is None or new is old:
            continue
        d = new.astype(jnp.float32) - old.astype(jnp.float32)
        usq = usq + jnp.sum(d * d)
    gnorm = jnp.sqrt(gsq)
    unorm = jnp.sqrt(usq)
    lf = jnp.asarray(loss).astype(jnp.float32)
    loss_bad = (~jnp.isfinite(lf)).astype(jnp.int32)
    out = dict(tel)  # guard/loss-scale fields pass through untouched
    out.update({
        "steps": tel["steps"] + 1,
        "loss_sum": tel["loss_sum"] + lf,
        "loss_last": lf,
        "grad_norm_sum": tel["grad_norm_sum"] + gnorm,
        "grad_norm_last": gnorm,
        "update_norm_sum": tel["update_norm_sum"] + unorm,
        "update_norm_last": unorm,
        "nonfinite_grad_steps": tel["nonfinite_grad_steps"]
        + (nonfinite > 0).astype(jnp.int32),
        "nonfinite_loss_steps": tel["nonfinite_loss_steps"] + loss_bad,
    })
    return out


@dataclass
class StepTelemetry:
    """Host-side view of one telemetry window (the periodic fetch)."""

    steps: int
    loss_last: float
    loss_mean: float
    grad_norm_last: float
    grad_norm_mean: float
    update_norm_last: float
    update_norm_mean: float
    nonfinite_grad_steps: int
    nonfinite_loss_steps: int
    # resilience update guard (0 / 1.0 when the guard is not enabled)
    skipped_update_steps: int = 0
    loss_scale: float = 1.0
    # numerics observability (observe.numerics; None when the program
    # did not opt in): per-group dynamics + first-nonfinite provenance
    groups: Optional[Dict[str, Dict[str, float]]] = None
    first_nonfinite_op: Optional[Dict[str, Any]] = None

    def as_dict(self) -> Dict[str, Any]:
        out = {
            "steps": self.steps,
            "loss_last": self.loss_last,
            "loss_mean": self.loss_mean,
            "grad_norm_last": self.grad_norm_last,
            "grad_norm_mean": self.grad_norm_mean,
            "update_norm_last": self.update_norm_last,
            "update_norm_mean": self.update_norm_mean,
            "nonfinite_grad_steps": self.nonfinite_grad_steps,
            "nonfinite_loss_steps": self.nonfinite_loss_steps,
            "skipped_update_steps": self.skipped_update_steps,
            "loss_scale": self.loss_scale,
        }
        if self.groups is not None:
            out["groups"] = self.groups
        if self.first_nonfinite_op is not None:
            out["first_nonfinite_op"] = self.first_nonfinite_op
        return out

    @property
    def healthy(self) -> bool:
        return (self.nonfinite_grad_steps == 0
                and self.nonfinite_loss_steps == 0)


def fetch_telemetry(scope, reset: bool = True,
                    program=None) -> Optional[StepTelemetry]:
    """ONE host sync: pull the device accumulator out of `scope`,
    convert to a window summary, and (by default) re-zero it so the
    next window starts fresh.  Returns None when the scope carries no
    telemetry (program not enabled, or no step ran yet).

    `program`: when given and the window latched a nonfinite bitmap
    (observe.numerics), the first set bit is joined back to the fluid
    op desc — `first_nonfinite_op` then carries op type/index/group,
    not just the index."""
    raw = scope.find_var(TELEMETRY_VAR)
    if raw is None:
        return None
    host: Dict[str, Any] = {}
    for k, v in raw.items():
        a = np.asarray(v)
        host[k] = a.item() if a.ndim == 0 else a
    if reset:
        # re-zero by SHAPE (scalars and numerics vectors alike) so the
        # next window starts fresh whatever fields this program carries
        fresh: Dict[str, Any] = {}
        for k, v in raw.items():
            if k in _PERSISTENT_FIELDS:  # loss-scale schedule survives
                fresh[k] = raw[k]
            else:
                a = np.asarray(v)
                fresh[k] = (np.zeros_like(a) if a.ndim
                            else a.dtype.type(0))
        scope.set_var(TELEMETRY_VAR, fresh)
    groups = first = None
    if "nonfinite_op_words" in host:
        from . import numerics as _numerics

        groups = _numerics.summarize_groups(host)
        if int(host.get(_numerics.NONFINITE_LATCH, 0)):
            first = _numerics.join_first_nonfinite(
                host[_numerics.NONFINITE_WORDS], program=program)
    n = max(int(host["steps"]), 1)
    return StepTelemetry(
        steps=int(host["steps"]),
        loss_last=host["loss_last"],
        loss_mean=host["loss_sum"] / n,
        grad_norm_last=host["grad_norm_last"],
        grad_norm_mean=host["grad_norm_sum"] / n,
        update_norm_last=host["update_norm_last"],
        update_norm_mean=host["update_norm_sum"] / n,
        nonfinite_grad_steps=int(host["nonfinite_grad_steps"]),
        nonfinite_loss_steps=int(host["nonfinite_loss_steps"]),
        skipped_update_steps=int(host.get("skipped_update_steps", 0)),
        loss_scale=float(host.get("loss_scale", 1.0)),
        groups=groups,
        first_nonfinite_op=first,
    )
