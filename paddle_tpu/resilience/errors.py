"""Structured error hierarchy for the resilience subsystem.

Every failure the subsystem handles — a corrupt checkpoint shard, a
torn save, a hung compile, exhausted retries — surfaces as a typed
exception carrying a machine-readable `details` dict (`as_dict()`),
mirroring the serving-side `ServingError` contract: a recovery layer
(Trainer fallback, CI chaos smoke, an alerting dashboard) dispatches
on `kind`, never by parsing message strings.
"""

from __future__ import annotations

from typing import Any, Dict


class ResilienceError(RuntimeError):
    """Base for structured resilience failures."""

    kind = "resilience_error"

    def __init__(self, message: str, **details: Any):
        super().__init__(message)
        self.details = details

    def as_dict(self) -> Dict[str, Any]:
        out = {"error": self.kind, "message": str(self)}
        out.update(self.details)
        return out


# ---------------------------------------------------------------------------
# Checkpoint integrity (io.py save_sharded/load_sharded, contrib.Trainer)
# ---------------------------------------------------------------------------

class CheckpointError(ResilienceError):
    """Base for checkpoint load/save failures.  `details` always carries
    the checkpoint `dirname`; Trainer attaches the `serial` it was
    attempting so a `ckpt_fallback` event names what it skipped."""

    kind = "checkpoint_error"


class CheckpointNotFoundError(CheckpointError):
    """No manifest at the expected path: the directory is not a
    (complete) checkpoint.  A save that died between shard write and
    manifest write lands here — the manifest is written LAST, so a torn
    checkpoint is indistinguishable from no checkpoint (by design)."""

    kind = "checkpoint_not_found"


class CheckpointCorruptError(CheckpointError):
    """The checkpoint exists but its content fails verification: a
    shard CRC32 mismatch, an unreadable/truncated shard container, a
    manifest or trainer-state file that is not valid JSON."""

    kind = "checkpoint_corrupt"


class CheckpointIncompleteError(CheckpointError):
    """The manifest references shard files/keys that are missing, or
    the present shards do not cover a requested slice."""

    kind = "checkpoint_incomplete"


class CheckpointFormatError(CheckpointError):
    """The checkpoint was written by an incompatible (newer) program
    format version."""

    kind = "checkpoint_format"


# ---------------------------------------------------------------------------
# Watchdog / retry (resilience/watchdog.py)
# ---------------------------------------------------------------------------

class WatchdogTimeout(ResilienceError):
    """A deadline-guarded region (compile, dispatch, warmup) exceeded
    its wall-clock budget."""

    kind = "watchdog_timeout"


class RetriesExhaustedError(ResilienceError):
    """A retried operation failed on every attempt; `details` carries
    the attempt count and the final error."""

    kind = "retries_exhausted"
