"""Optimizer classes: minimize = append_backward + update ops.

reference: python/paddle/fluid/optimizer.py — Optimizer.minimize (:295) =
append_backward + _create_optimization_pass (:198); SGD/Momentum/
LarsMomentum/Adagrad/Adam/Adamax/DecayedAdagrad/Adadelta/RMSProp/Ftrl
(:347-1407).  Update rules are ops (ops/optim.py) so the whole step —
forward, grads, updates — compiles into one XLA computation.
"""

from __future__ import annotations

import contextlib

from typing import Dict, List, Optional, Tuple

import numpy as np

from .clip import append_gradient_clip_ops
from .core.backward import append_backward
from .core.program import (Parameter, Program, Variable,
                           default_startup_program, program_guard)
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var: Optional[Variable] = None
        self.helper: Optional[LayerHelper] = None

    # -- learning rate ---------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is None:
            helper = LayerHelper(self.__class__.__name__)
            self._lr_var = helper.create_or_get_global_variable(
                name=f"{helper.name}.learning_rate", shape=[1],
                dtype="float32", persistable=True,
                initializer=Constant(float(self._learning_rate)))

    def _create_param_lr(self, param: Parameter) -> Variable:
        if getattr(param, "learning_rate", 1.0) == 1.0:
            return self._lr_var
        from . import layers

        return layers.scale(self._lr_var, scale=param.learning_rate)

    # -- accumulators ----------------------------------------------------
    def _add_accumulator(self, name: str, param: Parameter,
                         fill_value: float = 0.0, shape=None,
                         dtype=None) -> Variable:
        acc = self._accumulators.setdefault(name, {})
        if param.name in acc:
            return acc[param.name]
        helper = self.helper or LayerHelper(self.__class__.__name__)
        var = helper.create_or_get_global_variable(
            name=f"{param.name}.{name}",
            shape=list(shape if shape is not None else param.shape),
            dtype=dtype or param.dtype, persistable=True,
            initializer=Constant(fill_value))
        acc[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- main entry points ----------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        block = params_grads[0][0].block
        self._create_global_learning_rate()
        for p, g in params_grads:
            self._create_accumulators(block, p)
        opt_ops = []
        for p, g in params_grads:
            opt_ops.append(self._append_optimize_op(block, p, g))
        self._finish_update(block, params_grads)
        return opt_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        self.helper = LayerHelper(self.__class__.__name__)
        program = loss.block.program
        with program_guard(program, startup_program or
                           default_startup_program()):
            params_grads = self.backward(loss, startup_program,
                                         parameter_list, no_grad_set)
            opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    # -- per-optimizer hooks ---------------------------------------------
    def _create_accumulators(self, block, param):
        pass

    def _append_optimize_op(self, block, param, grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param, grad):
        return block.append_op(
            type="sgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, param):
        self._add_accumulator("velocity", param)

    def _append_optimize_op(self, block, param, grad):
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="momentum",
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov})


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, param):
        self._add_accumulator("velocity", param)

    def _append_optimize_op(self, block, param, grad):
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, param):
        self._add_accumulator("moment1", param)
        self._add_accumulator("moment2", param)
        self._add_accumulator("beta1_pow_acc", param, self._beta1, [1])
        self._add_accumulator("beta2_pow_acc", param, self._beta2, [1])

    def _append_optimize_op(self, block, param, grad):
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="adam",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, param):
        self._add_accumulator("moment", param)
        self._add_accumulator("inf_norm", param)
        self._add_accumulator("beta1_pow_acc", param, self._beta1, [1])

    def _append_optimize_op(self, block, param, grad):
        m = self._get_accumulator("moment", param)
        inf = self._get_accumulator("inf_norm", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        op = block.append_op(
            type="adamax",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "InfNorm": [inf], "Beta1Pow": [b1p],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "MomentOut": [m],
                     "InfNormOut": [inf]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})
        # beta1_pow updated separately (reference adamax has no pow output)
        block.append_op(type="scale", inputs={"X": [b1p]},
                        outputs={"Out": [b1p]},
                        attrs={"scale": self._beta1, "bias": 0.0,
                               "bias_after_scale": True})
        return op


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, param):
        self._add_accumulator("moment", param, self._initial)

    def _append_optimize_op(self, block, param, grad):
        m = self._get_accumulator("moment", param)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, param):
        self._add_accumulator("moment", param)

    def _append_optimize_op(self, block, param, grad):
        m = self._get_accumulator("moment", param)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "MomentOut": [m]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, param):
        self._add_accumulator("_avg_squared_grad", param)
        self._add_accumulator("_avg_squared_update", param)

    def _append_optimize_op(self, block, param, grad):
        g2 = self._get_accumulator("_avg_squared_grad", param)
        u2 = self._get_accumulator("_avg_squared_update", param)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [g2], "AvgSquaredUpdate": [u2],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [g2],
                     "AvgSquaredUpdateOut": [u2]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, param):
        self._add_accumulator("momentum", param)
        self._add_accumulator("mean_square", param)
        if self._centered:
            self._add_accumulator("mean_grad", param)

    def _append_optimize_op(self, block, param, grad):
        mom = self._get_accumulator("momentum", param)
        ms = self._get_accumulator("mean_square", param)
        ins = {"Param": [param], "Grad": [grad], "Moment": [mom],
               "MeanSquare": [ms],
               "LearningRate": [self._create_param_lr(param)]}
        outs = {"ParamOut": [param], "MomentOut": [mom],
                "MeanSquareOut": [ms]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", param)
            ins["MeanGrad"] = [mg]
            outs["MeanGradOut"] = [mg]
        return block.append_op(
            type="rmsprop", inputs=ins, outputs=outs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, param):
        self._add_accumulator("squared", param)
        self._add_accumulator("linear", param)

    def _append_optimize_op(self, block, param, grad):
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param)]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


# fluid exposes both CamelCase and the short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer


class _ScopeSwapMixin:
    """Shared apply/restore protocol: back up params, install
    `_swap_values(param)` for each, restore on exit (the scope-swap both
    ModelAverage.apply and ExponentialMovingAverage.apply perform in the
    reference)."""

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp

        from .core.executor import global_scope

        scope = global_scope()
        self._backup = {p.name: scope.find_var(p.name)
                        for p in self._params}
        for p in self._params:
            scope.set_var(p.name, jnp.asarray(
                np.asarray(self._swap_values(p)).astype("float32")))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from .core.executor import global_scope

        scope = global_scope()
        for name, val in getattr(self, "_backup", {}).items():
            scope.set_var(name, val)
        self._backup = {}


class ModelAverage(_ScopeSwapMixin, Optimizer):
    """Windowed parameter averaging for evaluation
    (reference: python/paddle/fluid/optimizer.py:1407 ModelAverage +
    operators/optimizers/average_accumulates_op.cc).

    Build AFTER minimize(); accumulation ops are appended to the main
    program so every training step updates the window sums.  Use
    `with ma.apply(exe): ...` to evaluate with averaged weights and
    restore afterwards.
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self.helper = LayerHelper(self.__class__.__name__)
        from .core.program import default_main_program

        program = default_main_program()
        block = program.global_block()
        self._params = [p for p in block.all_parameters()
                        if getattr(p, "trainable", True)]
        for param in self._params:
            self._append_average_accumulate_op(param)

    def _append_average_accumulate_op(self, param):
        s1 = self._add_accumulator("sum_1", param)
        s2 = self._add_accumulator("sum_2", param)
        s3 = self._add_accumulator("sum_3", param)
        num_acc = self._add_accumulator("num_accumulates", param,
                                        shape=[1], dtype="float32")
        old_num = self._add_accumulator("old_num_accumulates", param,
                                        shape=[1], dtype="float32")
        num_upd = self._add_accumulator("num_updates", param,
                                        shape=[1], dtype="float32")
        self.helper.append_op(
            type="average_accumulates",
            inputs={"Param": [param], "Sum1": [s1], "Sum2": [s2],
                    "Sum3": [s3], "NumAccumulates": [num_acc],
                    "OldNumAccumulates": [old_num],
                    "NumUpdates": [num_upd]},
            outputs={"Sum1Out": [s1], "Sum2Out": [s2], "Sum3Out": [s3],
                     "NumAccumulatesOut": [num_acc],
                     "OldNumAccumulatesOut": [old_num],
                     "NumUpdatesOut": [num_upd]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window})

    def _swap_values(self, param):
        """value to install during apply() — window average."""
        return self._averaged_value(param)

    def _averaged_value(self, param):
        from .core.executor import global_scope

        scope = global_scope()
        import numpy as np

        s1 = np.asarray(scope.find_var(f"{param.name}.sum_1"))
        s2 = np.asarray(scope.find_var(f"{param.name}.sum_2"))
        s3 = np.asarray(scope.find_var(f"{param.name}.sum_3"))
        na = float(np.asarray(
            scope.find_var(f"{param.name}.num_accumulates")).reshape(()))
        on = float(np.asarray(scope.find_var(
            f"{param.name}.old_num_accumulates")).reshape(()))
        total = na + on
        if total <= 0:
            return np.asarray(scope.find_var(param.name))
        return (s1 + s2 + s3) / total


class ExponentialMovingAverage(_ScopeSwapMixin):
    """EMA shadow weights with apply/restore (fluid's
    ExponentialMovingAverage; built here on a fused ema_accumulate op
    instead of the reference's scale/sum op composition).  apply() uses
    the bias-corrected shadow ema / (1 - decay^t), matching the
    reference's correction against the zero initialization."""

    def __init__(self, decay=0.999, name=None):
        self._decay = float(decay)
        self.helper = LayerHelper("ema", name=name)
        from .core.program import default_main_program

        block = default_main_program().global_block()
        self._params = [p for p in block.all_parameters()
                        if getattr(p, "trainable", True)]
        self._ema_vars = {}
        for p in self._params:
            ema = self.helper.create_or_get_global_variable(
                name=f"{p.name}.ema", shape=list(p.shape), dtype=p.dtype,
                persistable=True, initializer=Constant(0.0))
            self._ema_vars[p.name] = ema
        self._step_var = self.helper.create_or_get_global_variable(
            name=f"{self.helper.name}.ema_step", shape=[1],
            dtype="float32", persistable=True, initializer=Constant(0.0))

    def update(self):
        """Append the per-step EMA update ops (call after minimize)."""
        from . import layers

        for p in self._params:
            ema = self._ema_vars[p.name]
            self.helper.append_op(
                type="ema_accumulate",
                inputs={"Param": [p], "Ema": [ema]},
                outputs={"EmaOut": [ema]},
                attrs={"decay": self._decay})
        layers.increment(self._step_var, value=1.0, in_place=True)

    def _swap_values(self, param):
        import numpy as np

        from .core.executor import global_scope

        scope = global_scope()
        ema = np.asarray(scope.find_var(f"{param.name}.ema"))
        t = float(np.asarray(
            scope.find_var(self._step_var.name)).reshape(()))
        if t <= 0:
            return np.asarray(scope.find_var(param.name))
        correction = 1.0 - self._decay ** t
        return ema / correction
