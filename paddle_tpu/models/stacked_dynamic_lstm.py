"""Stacked LSTM language/sentiment model.

reference: benchmark/fluid/models/stacked_dynamic_lstm.py — embedding →
stacked dynamic_lstm layers → max pool over time → fc softmax.
"""

from __future__ import annotations

import numpy as np

from .. import layers, optimizer


def build_model(vocab_size=5147, emb_dim=512, hidden_dim=512,
                stacked_num=3, class_num=2, max_len=128,
                learning_rate=1e-3, with_optimizer=True,
                use_amp=False, pallas_rnn=False, rnn_unroll=1):
    """`pallas_rnn` routes every dynamic_lstm through the blocked fused
    Pallas recurrence kernel; `rnn_unroll` unrolls the lax.scan path by
    that factor — the two scan-bound levers (docs/RNN.md), A/B'd by
    tools/run_ab.py lstm variants."""
    data = layers.data(name="words", shape=[max_len], dtype="int64",
                       lod_level=1, append_batch_size=True)
    label = layers.data(name="label", shape=[1], dtype="int64")

    emb = layers.embedding(input=data, size=[vocab_size, emb_dim])
    # wire the sequence-length companion through the embedding output
    from ..layers.sequence import _propagate_seq_len

    _propagate_seq_len(data, emb)

    sentence = layers.fc(emb, size=hidden_dim * 4, act="tanh",
                         num_flatten_dims=2)
    _propagate_seq_len(data, sentence)
    lstm_out, _cell = layers.dynamic_lstm(sentence, size=hidden_dim * 4,
                                          use_peepholes=False,
                                          use_pallas=pallas_rnn,
                                          unroll=rnn_unroll)
    inputs = lstm_out
    for _ in range(stacked_num - 1):
        fc_in = layers.fc(inputs, size=hidden_dim * 4, num_flatten_dims=2)
        _propagate_seq_len(inputs, fc_in)
        inputs, _c = layers.dynamic_lstm(fc_in, size=hidden_dim * 4,
                                         use_peepholes=False,
                                         use_pallas=pallas_rnn,
                                         unroll=rnn_unroll)

    last = layers.sequence_pool(inputs, pool_type="max")
    logit = layers.fc(last, size=class_num, act="softmax")
    cost = layers.cross_entropy(input=logit, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=logit, label=label)
    if with_optimizer:
        opt = optimizer.AdamOptimizer(learning_rate=learning_rate)
        if use_amp:
            from .. import amp as amp_mod

            opt = amp_mod.decorate(opt)
        opt.minimize(avg_cost)
    return {"loss": avg_cost, "accuracy": acc,
            "feeds": ["words", "words.seq_len", "label"]}


def make_fake_batch(batch_size, max_len=128, vocab_size=5147, seed=0):
    rng = np.random.RandomState(seed)
    words = rng.randint(0, vocab_size, (batch_size, max_len)).astype(np.int64)
    lens = rng.randint(max_len // 2, max_len + 1,
                       (batch_size,)).astype(np.int32)
    label = rng.randint(0, 2, (batch_size, 1)).astype(np.int64)
    return {"words": words, "words.seq_len": lens, "label": label}
