"""Misc infra ops: print (in-graph tensor dump) and py_func (call back
into Python from a compiled program).

reference: paddle/fluid/operators/print_op.cc (debug dump with
print_phase/summarize), operators/py_func_op.cc (registered python
callables invoked by the executor).

TPU-native mapping: `print` → jax.debug.print (works inside jit,
streams from device asynchronously); `py_func` → jax.pure_callback
(host round-trip per call — correctness escape hatch, not a fast path).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, out

# py_func registry: attr carries an integer handle (serialization-safe),
# resolved here at trace time (reference py_func_op.cc keeps a static
# vector of PyObject callables the same way).
_PY_FUNCS: Dict[int, Callable] = {}


def register_py_func(fn: Callable) -> int:
    handle = len(_PY_FUNCS)
    _PY_FUNCS[handle] = fn
    return handle


@register_op("print")
def print_op(ctx, ins, attrs):
    """Pass-through with a device-side debug dump (reference
    print_op.cc: message, summarize, print_tensor_* knobs)."""
    x = first(ins, "In")
    message = attrs.get("message", "")
    summarize = int(attrs.get("summarize", 20))
    if summarize > 0:
        flat_preview = x.reshape(-1)[:summarize]
    else:
        flat_preview = x
    jax.debug.print(
        "{msg} shape={shape} dtype={dtype} data={data}",
        msg=message or "print_op", shape=str(x.shape),
        dtype=str(x.dtype), data=flat_preview)
    return out(Out=x)


@register_op("py_func")
def py_func(ctx, ins, attrs):
    """Invoke a registered python callable on host (reference
    py_func_op.cc).  attrs: handle (from register_py_func), out_shapes,
    out_dtypes describing the callable's outputs."""
    handle = int(attrs["handle"])
    fn = _PY_FUNCS.get(handle)
    if fn is None:
        raise KeyError(f"py_func handle {handle} is not registered in "
                       f"this process (handles do not serialize)")
    xs = ins.get("X", [])
    shapes = attrs.get("out_shapes", [])
    dtypes = attrs.get("out_dtypes", [])

    def resolve(shape):
        # a declared dynamic dim (-1) resolves to the first input's batch
        # at trace time (pure_callback needs concrete result shapes)
        resolved = []
        for d in shape:
            if d == -1:
                if not xs:
                    raise ValueError(
                        "py_func output declared with -1 dim but the op "
                        "has no inputs to infer the batch from")
                resolved.append(xs[0].shape[0])
            else:
                resolved.append(int(d))
        return tuple(resolved)

    result_shape = [
        jax.ShapeDtypeStruct(resolve(s), jnp.dtype(d))
        for s, d in zip(shapes, dtypes)
    ]

    def host_fn(*arrays):
        import numpy as np

        res = fn(*arrays)
        if not isinstance(res, (list, tuple)):
            res = (res,)
        return tuple(np.asarray(r, dtype=jnp.dtype(d))
                     for r, d in zip(res, dtypes))

    results = jax.pure_callback(host_fn, tuple(result_shape), *xs)
    return {"Out": list(results)}


@register_op("conv_shift")
def conv_shift(ctx, ins, attrs):
    """Circular (modular) correlation of two vector batches as used by
    Neural Turing Machines (reference conv_shift_op.cc):
    Out[b, i] = sum_{j=-(N-1)/2}^{(N-1)/2} X[b, (i+j) mod M] * Y[b, j'].
    X (B, M), Y (B, N) with N odd and N <= M; Out (B, M)."""
    x = first(ins, "X")
    y = first(ins, "Y")
    m, n = x.shape[1], y.shape[1]
    half = (n - 1) // 2
    # gather X at the circularly shifted positions for every tap: XLA
    # lowers the static roll stack to a single gather/concat fusion
    shifted = jnp.stack(
        [jnp.roll(x, -j, axis=1) for j in range(-half, half + 1)], axis=1
    )  # (B, N, M)
    o = jnp.einsum("bnm,bn->bm", shifted, y)
    return out(Out=o.astype(x.dtype))


@register_op("random_crop")
def random_crop(ctx, ins, attrs):
    """Per-instance random spatial crop (reference random_crop_op.cc):
    X (N, d1..dk) cropped to attr `shape` over the trailing len(shape)
    dims; each instance draws its own uniform offsets.  The reference
    threads a Seed tensor through; here randomness comes from the
    program RNG state (ctx.rng()), which advances per step."""
    x = first(ins, "X")
    shape = [int(s) for s in attrs["shape"]]
    k = len(shape)
    batch_dims = x.shape[: x.ndim - k]
    n = 1
    for d in batch_dims:
        n *= d
    flat = x.reshape((n,) + x.shape[x.ndim - k:])
    keys = jax.random.split(ctx.rng(), n * k).reshape(n, k, 2)

    def one(inst, ks):
        starts = [jax.random.randint(ks[i], (), 0,
                                     inst.shape[i] - shape[i] + 1)
                  for i in range(k)]
        return jax.lax.dynamic_slice(inst, starts, shape)

    o = jax.vmap(one)(flat, keys)
    return out(Out=o.reshape(batch_dims + tuple(shape)).astype(x.dtype))
