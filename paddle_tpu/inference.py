"""Inference serving: AOT-compiled Predictor + portable export.

TPU-native analog of the reference inference API
(reference: paddle/fluid/inference/api/analysis_predictor.cc:56
AnalysisPredictor — load model, run analysis/fusion passes, serve with a
NaiveExecutor and zero-copy tensors; api/paddle_analysis_config.h
AnalysisConfig; api/paddle_api.h PaddlePredictor ABI).

Mapping:
- the analysis/fusion pass pipeline → XLA compilation (the whole pruned
  program is jitted once; fusion is the compiler's job),
- AnalysisPredictor's warm NaiveExecutor loop → an AOT-compiled
  executable cached per input signature; params stay device-resident
  between calls (the zero-copy contract),
- the `__model__` + params dir → same layout (io.py), plus an optional
  portable serialized artifact (`__model__.export`, jax.export/StableHLO
  bytes) that loads WITHOUT re-tracing the program — the saved-engine
  analog of the reference's TensorRT serialized engines.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.executor import (RNG_STATE_VAR, Scope, interpret_program,
                            prune_ops)
from .core.program import Program
from .io import EXPORT_FILENAME, load_inference_model


class AnalysisConfig:
    """reference: api/paddle_analysis_config.h (knobs that map to XLA are
    kept; GPU/MKLDNN/TensorRT switches are parity no-ops on TPU)."""

    def __init__(self, model_dir: Optional[str] = None):
        self.model_dir = model_dir
        self.use_serialized_artifact = True
        self.use_int8 = False
        self._params_file = None
        self._model_file = None

    # -- fluid-style setters (parity) -----------------------------------
    def set_model(self, model_dir: str):
        self.model_dir = model_dir

    def enable_int8(self):
        """Serve with REAL int8 kernels: trained QAT scales freeze into
        quantized_conv2d/quantized_matmul ops (int8 MXU path) at load
        time (quantize.py convert_to_int8).  The model must have been
        exported from a QAT-transpiled program; models without the QAT
        pattern load unchanged.  Reference analog:
        enable_tensorrt_engine(precision=Int8) /
        enable_mkldnn_quantizer() in paddle_analysis_config.h."""
        self.use_int8 = True
        # int8 rewrites happen after load; a serialized float artifact
        # would silently serve fp — disable it for this predictor
        self.use_serialized_artifact = False
        return self

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, _on=True):
        pass  # XLA always optimizes

    def enable_memory_optim(self):
        pass  # XLA buffer liveness


class Predictor:
    """AOT inference engine (reference AnalysisPredictor::Run,
    analysis_predictor.cc:170, ZeroCopyRun :444).

    run(feed) compiles on first use per input signature
    (`.lower().compile()`, no retracing afterwards) and keeps parameters
    device-resident.  When the export dir carries a serialized artifact
    and the input signature matches, the artifact is used directly — no
    tracing at all (cold-start path).
    """

    def __init__(self, config: AnalysisConfig | str):
        if isinstance(config, str):
            config = AnalysisConfig(config)
        self.config = config
        from .core.executor import Executor

        self._scope = Scope()
        from .core.executor import scope_guard

        exe = Executor()
        self.int8_converted: Dict[int, tuple] = {}
        with scope_guard(self._scope):
            self._program, self._feed_names, fetch_vars = \
                load_inference_model(config.model_dir, exe)
            if config.use_int8:
                from .quantize import convert_to_int8

                self.int8_converted = convert_to_int8(self._program,
                                                      self._scope)
        self._fetch_names = [v.name for v in fetch_vars]
        import jax

        # params to device once (zero-copy across run() calls)
        self._params = {
            n: jax.device_put(v) for n, v in self._scope.vars.items()
            if v is not None and n != RNG_STATE_VAR
        }
        self._compiled: Dict[tuple, object] = {}
        self._exported = None
        self._export_sig = None
        path = os.path.join(config.model_dir, EXPORT_FILENAME)
        if config.use_serialized_artifact and os.path.exists(path):
            import json

            from jax import export as jax_export

            with open(path, "rb") as f:
                self._exported = jax_export.deserialize(f.read())
            sig_path = path + ".json"
            if os.path.exists(sig_path):
                # the artifact is tied to the exact __model__ it was
                # exported from; a re-saved model or a malformed/old-
                # format sidecar invalidates it rather than crashing or
                # silently serving the old graph
                try:
                    with open(sig_path) as f:
                        meta = json.load(f)
                    ok = (isinstance(meta, dict)
                          and meta.get("model_hash")
                          == _model_hash(config.model_dir))
                    if ok:
                        self._export_sig = tuple(
                            (n, tuple(s), d)
                            for n, s, d in meta["signature"])
                except (ValueError, KeyError, TypeError, OSError):
                    ok = False
                if not ok:
                    self._exported = None

    # -- introspection (PaddlePredictor parity) -------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    # -- execution ------------------------------------------------------
    def _signature(self, feeds):
        # feeds are jnp arrays by the time this is called: .shape/.dtype
        # are metadata reads, no device→host transfer
        return tuple(sorted((n, tuple(v.shape), str(v.dtype))
                            for n, v in feeds.items()))

    def _exported_matches(self, feeds) -> bool:
        """The artifact serves a request only when the per-input
        (name, shape, dtype) signature recorded at export time matches
        exactly; anything else falls back to the traced path."""
        if self._exported is None or self._export_sig is None:
            return False
        return self._signature(feeds) == self._export_sig

    def compile_signature(self, feed_spec: Dict[str, object],
                          donate_feeds: bool = False):
        """AOT-compile the inference executable for one input signature
        WITHOUT example data (the serving warmup path: feed_spec maps
        input name → jax.ShapeDtypeStruct).  The executable lands in
        the same per-signature cache run() consults, so a later run()
        with feeds of exactly this signature dispatches the precompiled
        executable — serving.ServingEngine precompiles its whole shape-
        bucket ladder through here and then never compiles again.

        donate_feeds=True donates the feed buffers to XLA (outputs may
        reuse input memory — the right call for a serving engine that
        pads a FRESH host batch per dispatch).  Do not enable it on a
        Predictor that is also run() with device-resident feeds reused
        across calls (e.g. benchmark(zero_copy=True)): a donated buffer
        is dead after the call.  Params are never donated.

        Idempotent per signature; returns the compiled executable."""
        import jax

        sig = tuple(sorted(
            (n, tuple(s.shape), str(np.dtype(s.dtype)))
            for n, s in feed_spec.items()))
        entry = self._compiled.get(sig)
        if entry is not None:
            return entry
        program = self._program
        fetch_names = self._fetch_names

        def infer(params, feeds):
            env = dict(params)
            env.update(feeds)
            env = interpret_program(program, env, None,
                                    fetch_names=tuple(fetch_names))
            return [env[n] for n in fetch_names]

        jitted = (jax.jit(infer, donate_argnums=(1,)) if donate_feeds
                  else jax.jit(infer))
        entry = jitted.lower(self._params, dict(feed_spec)).compile()
        self._compiled[sig] = entry
        return entry

    def run(self, feed: Dict[str, np.ndarray] | Sequence[np.ndarray]):
        """Returns fetch arrays (list, fetch order from export)."""
        import jax
        import jax.numpy as jnp

        if not isinstance(feed, dict):
            if len(feed) != len(self._feed_names):
                raise ValueError(
                    f"expected {len(self._feed_names)} inputs "
                    f"({self._feed_names}), got {len(feed)}")
            feed = dict(zip(self._feed_names, feed))
        feeds = {n: jnp.asarray(v) for n, v in feed.items()}

        sig = self._signature(feeds)
        entry = self._compiled.get(sig)
        # an already-compiled executable beats the serialized artifact
        # (the artifact exists to skip TRACING on cold start; its own
        # first .call still pays an XLA compile — a warmed signature,
        # e.g. a serving bucket precompiled via compile_signature, must
        # never fall back to that and recompile post-warmup)
        if entry is None and self._exported_matches(feeds):
            outs = self._exported.call(
                {n: self._params[n] for n in sorted(self._params)},
                {n: feeds[n] for n in sorted(feeds)})
            return [np.asarray(o) for o in outs]

        if entry is None:
            program = self._program
            fetch_names = self._fetch_names

            def infer(params, feeds):
                env = dict(params)
                env.update(feeds)
                env = interpret_program(program, env, None,
                                        fetch_names=tuple(fetch_names))
                return [env[n] for n in fetch_names]

            lowered = jax.jit(infer).lower(self._params, feeds)
            entry = lowered.compile()
            self._compiled[sig] = entry
        return [np.asarray(o) for o in entry(self._params, feeds)]

    def benchmark(self, feed, iters: int = 50, warmup: int = 5,
                  zero_copy: bool = True):
        """Serving latency probe: returns {p50_ms, mean_ms}.

        zero_copy=True places the inputs on device once and times the
        warm executable (the reference's ZeroCopyRun measurement,
        analysis_predictor.cc:444); zero_copy=False times end-to-end
        including host→device input transfer."""
        import jax
        import jax.numpy as jnp

        if zero_copy and isinstance(feed, dict):
            feed = {n: jax.device_put(jnp.asarray(v))
                    for n, v in feed.items()}
            for v in feed.values():
                v.block_until_ready()
        for _ in range(warmup):
            self.run(feed)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            self.run(feed)
            times.append((time.perf_counter() - t0) * 1e3)
        times.sort()
        result = {"p50_ms": times[len(times) // 2],
                  "mean_ms": sum(times) / len(times)}
        result["compute_ms"] = self._chained_latency_ms(feed)
        return result

    def _chained_latency_ms(self, feed, k: int = 20):
        """Per-inference device latency with host dispatch amortized over
        k chained requests (a lax.scan over k stacked copies of the
        input, so the body can't be loop-hoisted).  This is the number
        that matters when a real serving frontend keeps the device queue
        full; p50_ms above includes the host↔device round-trip, which in
        this environment is dominated by the tunnel."""
        import jax
        import jax.numpy as jnp

        feeds = {n: jnp.asarray(v) for n, v in feed.items()}
        program = self._program
        fetch_names = self._fetch_names

        def one(params, f):
            env = dict(params)
            env.update(f)
            env = interpret_program(program, env, None,
                                    fetch_names=tuple(fetch_names))
            return [env[n] for n in fetch_names]

        stacked = {n: jnp.stack([v] * k) for n, v in feeds.items()}

        def chained(params, xs):
            def body(_, f):
                return None, one(params, f)

            _, outs = jax.lax.scan(body, None, xs)
            return [o[-1] for o in outs]

        fn = jax.jit(chained).lower(self._params, stacked).compile()
        [o.block_until_ready() for o in fn(self._params, stacked)]
        t0 = time.perf_counter()
        [o.block_until_ready() for o in fn(self._params, stacked)]
        return (time.perf_counter() - t0) * 1e3 / k


    def clone(self) -> "Predictor":
        """Thread-safe sibling predictor SHARING device-resident weights
        and compiled executables (reference AnalysisPredictor::Clone,
        analysis_predictor.cc:56 — per-thread predictors over one
        parameter scope).  XLA executions are internally thread-safe and
        parameters are immutable at serving time, so clones share
        `_params`, `_compiled`, and the program; each clone only carries
        its own handle.  Typical use: one clone per serving thread."""
        twin = object.__new__(Predictor)
        twin.config = self.config
        twin.int8_converted = self.int8_converted
        twin._scope = self._scope
        twin._program = self._program
        twin._feed_names = self._feed_names
        twin._fetch_names = self._fetch_names
        twin._params = self._params          # shared device weights
        twin._compiled = self._compiled      # shared executable cache
        twin._exported = self._exported
        twin._export_sig = self._export_sig
        return twin


def create_paddle_predictor(config: AnalysisConfig) -> Predictor:
    """reference: CreatePaddlePredictor<AnalysisConfig>
    (analysis_predictor.cc:359)."""
    return Predictor(config)


def export_serialized_model(dirname: str, example_feed: Dict[str, np.ndarray],
                            executor=None):
    """AOT-export the saved inference model as a portable artifact
    (jax.export / StableHLO bytes) for the shapes of `example_feed`.
    Written next to `__model__` as `__model__.export`; Predictor uses it
    when input shapes match, skipping program re-tracing entirely.
    Replaces the reference's serialized-engine path
    (analysis_predictor.cc + tensorrt engine serialization)."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from .core.executor import Executor, scope_guard

    scope = Scope()
    exe = executor or Executor()
    with scope_guard(scope):
        program, feed_names, fetch_vars = load_inference_model(dirname, exe)
    fetch_names = [v.name for v in fetch_vars]
    params = {n: v for n, v in scope.vars.items()
              if v is not None and n != RNG_STATE_VAR}
    missing = set(feed_names) - set(example_feed)
    if missing:
        raise ValueError(f"example_feed missing inputs: {sorted(missing)}")

    def infer(params, feeds):
        env = dict(params)
        env.update(feeds)
        env = interpret_program(program, env, None,
                                fetch_names=tuple(fetch_names))
        return [env[n] for n in fetch_names]

    params_spec = {n: jax.ShapeDtypeStruct(np.shape(v),
                                           np.asarray(v).dtype)
                   for n, v in sorted(params.items())}
    feed_spec = {n: jax.ShapeDtypeStruct(np.shape(v),
                                         jnp.asarray(v).dtype)
                 for n, v in sorted(example_feed.items())}
    exported = jax_export.export(jax.jit(infer))(params_spec, feed_spec)
    path = os.path.join(dirname, EXPORT_FILENAME)
    with open(path, "wb") as f:
        f.write(exported.serialize())
    import json

    sig = sorted((n, list(s.shape), str(np.dtype(s.dtype)))
                 for n, s in feed_spec.items())
    with open(path + ".json", "w") as f:
        json.dump({"signature": sig,
                   "model_hash": _model_hash(dirname)}, f)
    return path


def _model_hash(dirname: str) -> str:
    import hashlib

    from .io import MODEL_FILENAME

    h = hashlib.sha256()
    with open(os.path.join(dirname, MODEL_FILENAME), "rb") as f:
        h.update(f.read())
    return h.hexdigest()
