"""Control-flow operators: sub-blocks lowered to lax primitives.

TPU-native analog of the reference's interpreter-level control flow
(reference: paddle/fluid/operators/controlflow/while_op.cc:50,125 — runs a
sub-block via a nested Executor with StepScopes; recurrent_op.cc:222 —
dynamic RNN over time steps; conditional_block_op.cc; beam_search_op.cc;
tensor_array_read_write_op.cc).  Instead of a nested interpreter with step
scopes, each macro op traces its sub-block *inside* a `lax.while_loop` /
`lax.scan` / `lax.switch` body, so the whole loop compiles to one XLA
computation with static shapes:

- `while`      → lax.while_loop over the loop-carried write set
- `switch`     → lax.switch over case sub-blocks (scalar conditions)
- `static_rnn` → lax.scan over the time dimension (differentiable; this is
                 the training-time recurrence, replacing recurrent_op's
                 replay-based gradient)
- `dynamic_rnn`→ lax.scan with per-example seq_len masking (padded+seq_len
                 replaces LoD / lod_rank_table reordering machinery)
- tensor arrays→ fixed-capacity (buffer, length) pairs with dynamic
                 update/index (replaces LoDTensorArray, which grew
                 dynamically — XLA requires a static capacity)
- `beam_search`/`beam_search_decode` → dense (batch, beam) top-k step and
                 reverse-scan backtrace (replaces the LoD-linked
                 beam_search_op.cc contract)

Divergence notes: `lax.while_loop` is not reverse-differentiable, so
training-time recurrence must use static_rnn/dynamic_rnn (scan); While is
for inference/decoding loops — matching how the reference's own while_grad
was in practice exercised only through RNN-style patterns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_macro_op, register_op
from .common import first, out


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

@register_macro_op("while")
def while_op(ctx, env, desc):
    """inputs: Condition (scalar bool var), X (outer reads, for pruning);
    outputs: Out (loop-carried vars: every outer var written in the body);
    attrs: sub_block (block index).

    The loop carry is [condition] + Out; the body re-traces the sub-block
    with carry values spliced into a copy of the surrounding env.
    """
    cond_name = desc.inputs["Condition"][0]
    out_names = [n for n in desc.outputs.get("Out", []) if n != cond_name]
    carry_names = [cond_name] + out_names
    sub_block = desc.attrs["sub_block"]

    def cond_fn(carry):
        return jnp.reshape(carry[0], ()).astype(bool)

    def body_fn(carry):
        e = dict(env)
        e.update(zip(carry_names, carry))
        ctx.run_block(sub_block, e)
        new = []
        for name, old in zip(carry_names, carry):
            v = e[name]
            # Keep carry dtypes stable (weak-type drift from python scalars
            # would change the carry signature between iterations).
            if hasattr(old, "dtype") and hasattr(v, "dtype") \
                    and v.dtype != old.dtype:
                v = v.astype(old.dtype)
            new.append(v)
        return tuple(new)

    init = tuple(env[n] for n in carry_names)
    final = lax.while_loop(cond_fn, body_fn, init)
    env.update(zip(carry_names, final))


# ---------------------------------------------------------------------------
# switch (scalar multi-way conditional; fluid layers.Switch / conditional_block)
# ---------------------------------------------------------------------------

@register_macro_op("switch")
def switch_op(ctx, env, desc):
    """inputs: Conditions (list of scalar bool vars, checked in order);
    outputs: Out (vars any case may write; must pre-exist in env);
    attrs: case_blocks (list of block indices, one per condition),
           default_block (block index or -1).

    Lowered to lax.switch: the selected branch index is the first true
    condition (or the default).  Like fluid's Switch (built on
    conditional_block_op.cc), untaken branches are not executed.
    """
    conds = [jnp.reshape(env[n], ()).astype(bool)
             for n in desc.inputs.get("Conditions", [])]
    out_names = desc.outputs.get("Out", [])
    case_blocks = list(desc.attrs["case_blocks"])
    default_block = desc.attrs.get("default_block", -1)

    # index of first true condition; len(conds) = default
    idx = jnp.asarray(len(conds), jnp.int32)
    for i in range(len(conds) - 1, -1, -1):
        idx = jnp.where(conds[i], jnp.asarray(i, jnp.int32), idx)

    def make_branch(block_idx):
        def branch(operand):
            if block_idx < 0:
                return operand
            e = dict(env)
            e.update(zip(out_names, operand))
            ctx.run_block(block_idx, e)
            return tuple(
                jnp.asarray(e[n]).astype(o.dtype).reshape(o.shape)
                for n, o in zip(out_names, operand))
        return branch

    branches = [make_branch(b) for b in case_blocks]
    branches.append(make_branch(default_block))
    operand = tuple(jnp.asarray(env[n]) for n in out_names)
    result = lax.switch(idx, branches, operand)
    env.update(zip(out_names, result))


# ---------------------------------------------------------------------------
# static_rnn (lax.scan; fluid recurrent_op / StaticRNN)
# ---------------------------------------------------------------------------

@register_macro_op("static_rnn")
def static_rnn_op(ctx, env, desc):
    """attrs:
      sub_block:    block index of the step body
      step_inputs:  [[outer_name, inner_name]]  outer is time-major (T, ...)
      memories:     [[pre_name, post_name, init_name]]
      step_outputs: [[inner_name, outer_name]]  outer gets (T, ...) stacked
      final_states: [[post_name, outer_name]]   (optional)
      unroll:       lax.scan unroll factor (default 1) — the cheap
                    XLA-side scan-bound lever (fewer while iterations,
                    more work per iteration for the scheduler)

    reference: paddle/fluid/operators/recurrent_op.cc:222 (step-scope
    iteration) — here one lax.scan, reverse-differentiable by jax AD, so
    recurrent gradients need no replay machinery (recurrent_op.cc:311).
    """
    sub_block = desc.attrs["sub_block"]
    step_inputs = desc.attrs.get("step_inputs", [])
    memories = desc.attrs.get("memories", [])
    step_outputs = desc.attrs.get("step_outputs", [])
    final_states = desc.attrs.get("final_states", [])
    unroll = int(desc.attrs.get("unroll", 1))

    init_carry = tuple(env[init] for _pre, _post, init in memories)
    xs = tuple(env[outer] for outer, _inner in step_inputs)

    def body(carry, x_slices):
        e = dict(env)
        for (pre, _post, _init), c in zip(memories, carry):
            e[pre] = c
        for (_outer, inner), x in zip(step_inputs, x_slices):
            e[inner] = x
        ctx.run_block(sub_block, e)
        new_carry = tuple(
            e[post].astype(c.dtype) if hasattr(c, "dtype") else e[post]
            for (_pre, post, _init), c in zip(memories, carry))
        ys = tuple(e[inner] for inner, _outer in step_outputs)
        return new_carry, ys

    final, ys = lax.scan(body, init_carry, xs, unroll=unroll)
    for (_inner, outer), y in zip(step_outputs, ys):
        env[outer] = y
    # final is ordered by memories; final_states maps post->outer
    post_to_final = {post: f for (_pre, post, _init), f in zip(memories, final)}
    for post, outer in final_states:
        env[outer] = post_to_final[post]


# ---------------------------------------------------------------------------
# dynamic_rnn (scan + seq_len masking; fluid DynamicRNN w/o lod_rank_table)
# ---------------------------------------------------------------------------

@register_macro_op("dynamic_rnn")
def dynamic_rnn_op(ctx, env, desc):
    """Like static_rnn but over padded batch-major sequences (B, T, ...)
    with a per-example length vector: steps past an example's length leave
    its memory unchanged and emit zeros.  Replaces the reference's
    lod_rank_table / shrink_rnn_memory reorder-by-length machinery
    (operators/lod_rank_table_op.cc, shrink_rnn_memory_op.cc) — masking
    costs a few flops but keeps one static-shape scan, which is the right
    trade on the MXU.

    attrs: sub_block, step_inputs [[outer, inner]], memories
    [[pre, post, init]], step_outputs [[inner, outer]], final_states
    [[post, outer]], seq_len (name of the (B,) length var), unroll
    (lax.scan unroll factor, default 1).
    """
    sub_block = desc.attrs["sub_block"]
    step_inputs = desc.attrs.get("step_inputs", [])
    memories = desc.attrs.get("memories", [])
    step_outputs = desc.attrs.get("step_outputs", [])
    final_states = desc.attrs.get("final_states", [])
    seq_len = env[desc.attrs["seq_len"]]  # (B,) int
    unroll = int(desc.attrs.get("unroll", 1))

    init_carry = tuple(env[init] for _pre, _post, init in memories)
    # batch-major (B, T, ...) → time-major (T, B, ...) for the scan
    xs = tuple(jnp.moveaxis(env[outer], 1, 0) for outer, _inner in step_inputs)
    t_max = xs[0].shape[0] if xs else int(jnp.max(seq_len))

    def mask_like(active, val):
        # active: (B,) bool; val: (B, ...) — broadcast mask over trailing dims
        m = active.reshape(active.shape + (1,) * (val.ndim - 1))
        return m

    def body(carry, inp):
        t, x_slices = inp
        active = t < seq_len  # (B,)
        e = dict(env)
        for (pre, _post, _init), c in zip(memories, carry):
            e[pre] = c
        for (_outer, inner), x in zip(step_inputs, x_slices):
            e[inner] = x
        ctx.run_block(sub_block, e)
        new_carry = tuple(
            jnp.where(mask_like(active, e[post]), e[post].astype(c.dtype), c)
            for (_pre, post, _init), c in zip(memories, carry))
        ys = tuple(
            jnp.where(mask_like(active, e[inner]), e[inner],
                      jnp.zeros_like(e[inner]))
            for inner, _outer in step_outputs)
        return new_carry, ys

    ts = jnp.arange(t_max)
    final, ys = lax.scan(body, init_carry, (ts, xs), unroll=unroll)
    for (_inner, outer), y in zip(step_outputs, ys):
        env[outer] = jnp.moveaxis(y, 0, 1)  # back to (B, T, ...)
    post_to_final = {post: f for (_pre, post, _init), f in zip(memories, final)}
    for post, outer in final_states:
        env[outer] = post_to_final[post]


# ---------------------------------------------------------------------------
# calc_gradient (fluid backward.py:613 gradients/calc_gradient)
# ---------------------------------------------------------------------------

@register_macro_op("calc_gradient")
def calc_gradient_op(ctx, env, desc):
    """Gradients of target vars w.r.t. arbitrary input vars.

    attrs: op_range [start, stop) — the block-0 op span whose recomputation
    expresses targets as a pure function of inputs.  The impl re-traces
    those ops with the inputs as function arguments and applies jax.vjp;
    XLA CSE dedups the recomputed subgraph against the original trace.

    inputs: TargetGradients (optional cotangents, one per target, or absent
    → ones).  Targets/Inputs are carried by name in attrs because their
    values are taken from / spliced into the live env.
    """
    target_names = desc.attrs["targets"]
    input_names = set(desc.attrs["inputs"])
    input_order = desc.attrs["inputs"]
    grad_names = desc.outputs["InputGrads"]
    start, stop = desc.attrs["op_range"]
    span = ctx.program.blocks[desc.attrs.get("block", 0)].ops[start:stop]

    # Prune the span to the inputs→targets path (fluid _find_op_path_,
    # backward.py:573).  Two correctness requirements: (a) ops *producing*
    # an input var must not run, or they would overwrite the vjp-traced
    # binding and the gradient would be silently zero; (b) ops off the
    # path (e.g. branches over unfed data vars that the main run pruned)
    # must not run, or they would KeyError on absent env names.
    needed = set(target_names)
    keep_rev = []
    for op in reversed(span):
        outs = op.desc.output_names()
        if any(n in needed and n not in input_names for n in outs):
            keep_rev.append(op)
            needed.update(op.desc.input_names())
    ops = list(reversed(keep_rev))
    op_offset = {id(op): start + i for i, op in enumerate(span)}

    tg = desc.inputs.get("TargetGradients", [])

    def f(xs):
        e = dict(env)
        # the vjp replay re-traces ops already bitmapped by the main
        # forward — numerics provenance must not double-scan them
        e.pop("__numerics_bits__", None)
        e.update(zip(input_order, xs))
        from ..core.executor import run_ops

        # Re-trace with the *same* per-op RNG keys as the original forward
        # (same base key + op indices) so stochastic ops (dropout) replay
        # the identical realization and XLA CSE can merge the subgraphs.
        for op in ops:
            run_ops([op], e, ctx._rng_key, start_index=op_offset[id(op)],
                    amp_lists=ctx.amp_lists, program=ctx.program)
        return tuple(e[t] for t in target_names)

    primal_in = tuple(env[n] for n in input_order)
    _primals, vjp_fn = jax.vjp(f, primal_in)
    if tg:
        cotangents = tuple(env[n] for n in tg)
    else:
        cotangents = tuple(jnp.ones_like(env[t]) for t in target_names)
    (grads,) = vjp_fn(cotangents)
    env.update(zip(grad_names, grads))


# ---------------------------------------------------------------------------
# Tensor arrays (fixed-capacity analog of LoDTensorArray)
# ---------------------------------------------------------------------------
# Representation in env: a 2-tuple (buffer, length) where buffer has shape
# (capacity, *elem_shape) and length is an int32 scalar tracking the
# high-water mark.  Tuples are jax pytrees, so arrays flow through while
# carries transparently.
# reference: paddle/fluid/operators/controlflow/tensor_array_read_write_op.cc

@register_op("create_array")
def create_array(ctx, ins, attrs):
    shape = tuple(attrs["element_shape"])
    cap = int(attrs["capacity"])
    # canonicalize (int64→int32 when x64 is off) without warning spam
    dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(attrs.get("dtype",
                                                              "float32")))
    buf = jnp.zeros((cap,) + shape, dtype=dtype)
    return out(Out=(buf, jnp.asarray(0, jnp.int32)))


@register_op("array_write")
def array_write(ctx, ins, attrs):
    x = first(ins, "X")
    i = jnp.reshape(first(ins, "I"), ()).astype(jnp.int32)
    buf, length = first(ins, "Array")
    buf = lax.dynamic_update_index_in_dim(buf, x.astype(buf.dtype), i, 0)
    length = jnp.maximum(length, i + 1)
    return out(Out=(buf, length))


@register_op("array_read")
def array_read(ctx, ins, attrs):
    buf, _length = first(ins, "Array")
    i = jnp.reshape(first(ins, "I"), ()).astype(jnp.int32)
    return out(Out=lax.dynamic_index_in_dim(buf, i, 0, keepdims=False))


@register_op("array_length")
def array_length(ctx, ins, attrs):
    _buf, length = first(ins, "Array")
    return out(Out=length.reshape((1,)))


@register_op("array_to_tensor")
def array_to_tensor(ctx, ins, attrs):
    """Stack the written prefix (whole buffer; entries past `length` are
    zero).  Axis attr concatenates instead when axis >= 0 semantics of
    fluid's array_to_lod_tensor are not needed on padded tensors."""
    buf, length = first(ins, "Array")
    return out(Out=buf, OutIndex=length.reshape((1,)))


@register_op("max_sequence_len")
def max_sequence_len(ctx, ins, attrs):
    """Max over a (B,) length vector (reference: max_sequence_len_op from
    the lod_rank_table machinery; here lengths are explicit)."""
    sl = first(ins, "SeqLen")
    return out(Out=jnp.max(sl).reshape((1,)))


# ---------------------------------------------------------------------------
# Beam search (dense batch×beam formulation)
# ---------------------------------------------------------------------------

@register_op("beam_search")
def beam_search(ctx, ins, attrs):
    """One beam expansion step.

    inputs: PreIds (B, K) int32 — tokens chosen last step
            PreScores (B, K) f32 — cumulative log-probs
            Scores (B, K, V) f32 — log-probs of next-token candidates
    attrs:  beam_size K, end_id, is_first_step (bool: only beam 0 is live,
            others are -inf so the first expansion doesn't duplicate)
    outputs: SelectedIds (B, K), SelectedScores (B, K), ParentIdx (B, K)

    Finished beams (pre_id == end_id) are frozen: they propagate with
    unchanged score and re-emit end_id, so top-k naturally retires them.
    reference: paddle/fluid/operators/beam_search_op.cc:1 (LoD-linked
    variant); the dense (B, K) + parent-pointer formulation is the
    TPU-native equivalent (static shapes, one top_k per step).
    """
    pre_ids = first(ins, "PreIds")
    pre_scores = first(ins, "PreScores")
    scores = first(ins, "Scores")  # (B, K, V) log-probs
    B, K, V = scores.shape
    end_id = int(attrs.get("end_id", 1))
    neg_inf = jnp.asarray(-1e9, scores.dtype)

    finished = pre_ids == end_id  # (B, K)
    # Expansion scores: live beams add candidate log-probs; finished beams
    # keep exactly one candidate (end_id) at their frozen score.
    expand = pre_scores[:, :, None] + scores  # (B, K, V)
    onehot_end = jax.nn.one_hot(end_id, V, dtype=scores.dtype)  # (V,)
    frozen = pre_scores[:, :, None] + jnp.where(
        onehot_end.astype(bool), 0.0, neg_inf)  # (B, K, V)
    total = jnp.where(finished[:, :, None], frozen, expand)
    if attrs.get("is_first_step", False):
        # only beam 0 contributes candidates on the first step
        beam_mask = (jnp.arange(K) == 0)[None, :, None]
        total = jnp.where(beam_mask, total, neg_inf)

    flat = total.reshape(B, K * V)
    top_scores, top_idx = lax.top_k(flat, K)  # (B, K)
    parent = (top_idx // V).astype(jnp.int32)
    token = (top_idx % V).astype(pre_ids.dtype)
    return out(SelectedIds=token, SelectedScores=top_scores,
               ParentIdx=parent)


@register_op("beam_search_decode")
def beam_search_decode(ctx, ins, attrs):
    """Backtrace parent pointers into full sequences.

    inputs: Ids (T, B, K) int — tokens per step; Parents (T, B, K) int;
            NumSteps (scalar int, optional — entries past it are padding)
    attrs:  end_id
    outputs: SentenceIds (B, K, T) — right-padded with end_id;
             SentenceScores passthrough handled by caller.
    reference: beam_search_decode_op.cc (walks LoD links; here a reverse
    lax.scan over the parent-pointer arrays).
    """
    ids = first(ins, "Ids")  # (T, B, K)
    parents = first(ins, "Parents")
    T, B, K = ids.shape
    end_id = int(attrs.get("end_id", 1))
    num_steps = ins.get("NumSteps")
    n = (jnp.reshape(num_steps[0], ()).astype(jnp.int32)
         if num_steps else jnp.asarray(T, jnp.int32))

    batch_ix = jnp.arange(B)[:, None]  # (B, 1)

    def body(beam_ix, t):
        # beam_ix: (B, K) — which beam slot each final hypothesis occupied
        # at step t+1; gather token at t and hop to its parent.
        valid = t < n
        tok = jnp.where(valid, ids[t][batch_ix, beam_ix],
                        jnp.full((B, K), end_id, ids.dtype))
        prev = jnp.where(valid, parents[t][batch_ix, beam_ix], beam_ix)
        return prev, tok

    init = jnp.tile(jnp.arange(K, dtype=jnp.int32)[None, :], (B, 1))
    _final, toks = lax.scan(body, init, jnp.arange(T - 1, -1, -1))
    # toks: (T, B, K) in reverse time order → (B, K, T) forward
    seqs = jnp.moveaxis(toks[::-1], 0, 2)
    return out(SentenceIds=seqs)

@register_op("tensor_array_to_tensor")
def tensor_array_to_tensor(ctx, ins, attrs):
    """Concat (default) or stack the tensor-array buffer along `axis`
    (reference: operators/tensor_array_to_tensor_op.cc:154 concats a
    LoDTensorArray along axis, OutIndex recording each entry's size on
    that axis).  Fixed-capacity divergence: all T capacity slots
    participate (unwritten tail entries are zero) — the dense
    tensor-array protocol above."""
    buf, _length = first(ins, "X")
    use_stack = bool(attrs.get("use_stack", False))
    t = buf.shape[0]
    entry = buf.shape[1:]
    axis = _tat_axis(int(attrs.get("axis", 0)), len(entry), use_stack)
    moved = jnp.moveaxis(buf, 0, axis)
    if use_stack:
        o = moved
        index = jnp.ones((t,), jnp.int32)
    else:
        o = moved.reshape(entry[:axis] + (t * entry[axis],)
                          + entry[axis + 1:])
        index = jnp.full((t,), entry[axis], jnp.int32)
    return out(Out=o, OutIndex=index)


def _tat_axis(axis: int, rank: int, use_stack: bool) -> int:
    """Validate/normalize tensor_array_to_tensor's axis: stacking
    INSERTS a dim (valid positions 0..rank, like the reference
    StackOp); concatenation needs entries of rank >= 1 and a dim to
    concat on (0..rank-1)."""
    if not use_stack and rank == 0:
        raise ValueError(
            "tensor_array_to_tensor: cannot concat scalar entries — "
            "use use_stack=True to stack them into a vector")
    bound = rank + 1 if use_stack else rank
    if not -bound <= axis < bound:
        raise ValueError(
            f"tensor_array_to_tensor: axis {axis} out of range for "
            f"entry rank {rank} "
            f"({'stack inserts at 0..' + str(rank) if use_stack else 'concat needs 0..' + str(rank - 1)})")
    return axis % bound
