"""Program visualization / debugging aids.

reference: python/paddle/fluid/debugger.py + graphviz.py (program → dot),
framework/ir/graph_viz_pass.cc.
"""

from __future__ import annotations

from .core.program import Parameter, Program


def pprint_program_codes(program: Program) -> str:
    """Readable program listing (debugger.py pprint_program_codes)."""
    return str(program)


def draw_block_graphviz(block, path: str = None, highlights=None) -> str:
    """Emit a graphviz dot description of the block's dataflow
    (debugger.py draw_block_graphviz)."""
    lines = ["digraph G {", "  rankdir=TB;"]
    highlights = set(highlights or [])
    for name, var in block.vars.items():
        shape = "box" if isinstance(var, Parameter) else "ellipse"
        color = ', style=filled, fillcolor="#ffd37f"' \
            if name in highlights else ""
        label = f"{name}\\n{var.shape} {var.dtype}"
        lines.append(f'  "{name}" [shape={shape}, label="{label}"{color}];')
    for i, op in enumerate(block.ops):
        op_id = f"op_{i}_{op.type}"
        lines.append(
            f'  "{op_id}" [shape=record, style=filled, '
            f'fillcolor="#cde6ff", label="{op.type}"];')
        for n in op.desc.input_names():
            lines.append(f'  "{n}" -> "{op_id}";')
        for n in op.desc.output_names():
            lines.append(f'  "{op_id}" -> "{n}";')
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
