"""Operator registry: op type name → JAX implementation.

TPU-native analog of the reference kernel registry
(reference: paddle/fluid/framework/op_registry.h:197,237,240 —
REGISTER_OPERATOR / REGISTER_OP_*_KERNEL).  There is no per-device kernel
dispatch: every op has one traceable JAX implementation and XLA lowers it to
the target backend.  Grad kernels don't exist either — autodiff is jax.grad
over the traced program (see core/backward.py) instead of grad-op makers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

# impl signature: impl(ctx, ins: Dict[slot, List[Array]], attrs: Dict) ->
#                 Dict[slot, List[Array]]
OpImpl = Callable[..., Dict[str, List[Any]]]

_REGISTRY: Dict[str, OpImpl] = {}

# Macro ops are interpreter-level: their impls receive the whole environment
# and the OpDesc (signature impl(ctx, env, desc) -> None, mutating env) so
# they can trace sub-blocks into lax control-flow primitives.  TPU-native
# analog of the reference's interpreter-level control-flow operators
# (reference: paddle/fluid/operators/controlflow/while_op.cc:50 — ops that
# run sub-blocks via a nested Executor).
_MACRO_OPS: Dict[str, Any] = {}


def register_op(op_type: str):
    """Decorator registering an implementation for `op_type`."""

    def deco(fn: OpImpl) -> OpImpl:
        if op_type in _REGISTRY:
            raise ValueError(f"op {op_type!r} registered twice")
        _REGISTRY[op_type] = fn
        return fn

    return deco


def register_macro_op(op_type: str):
    """Decorator registering an interpreter-level (env + sub-block) op."""

    def deco(fn):
        if op_type in _MACRO_OPS or op_type in _REGISTRY:
            raise ValueError(f"op {op_type!r} registered twice")
        _MACRO_OPS[op_type] = fn
        return fn

    return deco


def is_macro_op(op_type: str) -> bool:
    return op_type in _MACRO_OPS


def get_macro_op_impl(op_type: str):
    return _MACRO_OPS[op_type]


def get_op_impl(op_type: str) -> OpImpl:
    impl = _REGISTRY.get(op_type)
    if impl is None:
        raise NotImplementedError(
            f"no implementation registered for op {op_type!r}; "
            f"known ops: {sorted(_REGISTRY)[:20]}..."
        )
    return impl


def has_op(op_type: str) -> bool:
    return op_type in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


class OpContext:
    """Per-execution context handed to op impls.

    Provides deterministic per-op PRNG keys derived from the step key
    (replaces the reference's per-op curand/seed attrs) and scope-level
    flags such as nan-check (reference FLAGS_check_nan_inf,
    paddle/fluid/framework/operator.cc:943).
    """

    def __init__(self, rng_key, op_index: int = 0, is_test: bool = False,
                 program=None, amp_lists=None, sparse_rows=None):
        self._rng_key = rng_key
        self.op_index = op_index
        self.is_test = is_test
        # Set when executing inside a Program trace; macro (control-flow)
        # ops use these to locate and interpret their sub-blocks.
        self.program = program
        self.amp_lists = amp_lists
        # op_index → pre-gathered embedding rows for the SelectedRows-style
        # sparse grad path (core/executor.py, ops/sparse.py lookup_table)
        self.sparse_rows = sparse_rows

    def rng(self):
        """A PRNG key unique to this op within the step."""
        import jax

        if self._rng_key is None:
            raise RuntimeError(
                "op requested randomness but executor has no RNG state"
            )
        return jax.random.fold_in(self._rng_key, self.op_index)

    def run_block(self, block_idx: int, env):
        """Trace a sub-block's ops over `env` (mutated in place).  Used by
        control-flow macro ops; the sub-block gets a distinct RNG stream so
        per-op keys don't collide with the parent block's."""
        import jax

        from .executor import run_ops

        if self.program is None:
            raise RuntimeError("OpContext has no program; sub-block "
                               "execution requires a program trace")
        # numerics provenance (observe pillar 6) attributes sub-block
        # ops to the OWNING macro op: sub-block op indices are
        # block-local and would corrupt the global per-op bitmap
        env.pop("__numerics_bits__", None)
        block = self.program.blocks[block_idx]
        sub_key = (None if self._rng_key is None
                   else jax.random.fold_in(self._rng_key, 7919 + block_idx))
        run_ops(block.ops, env, sub_key, amp_lists=self.amp_lists,
                program=self.program)
        return env
