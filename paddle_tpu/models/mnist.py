"""MNIST CNN (reference: benchmark/fluid/models/mnist.py cnn_model)."""

from __future__ import annotations

from .. import layers, nets, optimizer


def cnn_model(data):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    return layers.fc(input=conv_pool_2, size=10, act="softmax")


def build_model(learning_rate=0.001, with_optimizer=True):
    images = layers.data(name="pixel", shape=[1, 28, 28], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = cnn_model(images)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    batch_acc = layers.accuracy(input=predict, label=label)
    if with_optimizer:
        opt = optimizer.AdamOptimizer(learning_rate=learning_rate,
                                      beta1=0.9, beta2=0.999)
        opt.minimize(avg_cost)
    return {"loss": avg_cost, "accuracy": batch_acc,
            "feeds": ["pixel", "label"], "predict": predict}
