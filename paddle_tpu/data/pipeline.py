"""Device-fed input pipeline: double-buffered host→device prefetch.

TPU-native analog of the reference's device-side reader chain
(reference: paddle/fluid/operators/reader/buffered_reader.cc:1 — pinned-
memory double buffering; reader/create_py_reader_op.cc +
lod_tensor_blocking_queue.h — a Python thread feeding a blocking queue
the graph's read op pops; python/paddle/fluid/layers/io.py py_reader:633,
double_buffer:1002).

Design: a daemon thread pulls host batches from the user's reader,
starts their host→device transfers immediately (`jax.device_put` is
asynchronous — the copy overlaps the current training step), and parks
the in-flight device arrays in a bounded queue.  The training loop pops
ready feed dicts, so steady-state step time is max(compute, transfer)
instead of compute + transfer.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

import numpy as np

from .decorator import _ReaderError

_STOP = object()

# producer-exception classes retried by default: the transient I/O
# family (a flaky remote filesystem / dataset service).  A ValueError
# from a broken reader is NOT transient — it reproduces on replay, so
# retrying it would just burn the budget before the same _ReaderError.
DEFAULT_RETRYABLE: Tuple[type, ...] = (ConnectionError, TimeoutError)


def feed_signature(batch: Dict[str, Any]) -> Dict[str, Tuple[str, int]]:
    """The per-feed (dtype, ndim) signature validation locks onto
    after the first accepted batch — a drift would retrace the jitted
    step (feed-signature storm) before it produced a wrong number."""
    return {n: (str(np.asarray(v).dtype), int(np.asarray(v).ndim))
            for n, v in batch.items()}


def validate_feed_batch(batch: Dict[str, Any],
                        signature: Optional[Dict[str, Tuple[str, int]]]
                        = None) -> List[Dict[str, Any]]:
    """Host-side admission check, shared by DeviceFeeder(validate=True)
    and Trainer(validate_feed=True): every float feed must be finite,
    and (with a locked signature) dtypes/ndims must match the first
    accepted batch.  Returns a list of structured problems (empty =
    admit) — the payload of the `feed_quarantined` event."""
    problems: List[Dict[str, Any]] = []
    for name, v in batch.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            finite = np.isfinite(arr)
            if not finite.all():
                problems.append(
                    {"name": name, "problem": "nonfinite",
                     "bad_values": int(arr.size - int(finite.sum()))})
        if signature is not None:
            want = signature.get(name)
            got = (str(arr.dtype), int(arr.ndim))
            if want is None:
                problems.append({"name": name,
                                 "problem": "unknown_feed"})
            elif want != got:
                problems.append({"name": name,
                                 "problem": "signature_drift",
                                 "want": list(want),
                                 "got": list(got)})
    if signature is not None:
        for name in sorted(set(signature) - set(batch)):
            problems.append({"name": name, "problem": "missing_feed"})
    return problems


class DeviceFeeder:
    """Iterator of device-resident feed dicts with background prefetch.

    reader: callable returning an iterable of feed dicts
            ({name: np.ndarray}) — one dict per step.
    capacity: max in-flight prefetched batches (2 = classic double
              buffering; raise it to ride out producer jitter).
    validate: host-side admission check (validate_feed_batch) before
              any device_put is spent — a poisoned batch is dropped
              with a `feed_quarantined` event + counter instead of
              reaching the step.
    retryable: exception classes the producer treats as TRANSIENT:
               instead of killing the pass via _ReaderError it
               re-opens the reader, fast-forwards past the batches
               already produced (the reader must be deterministic —
               the same contract checkpoint resume already imposes),
               and retries with exponential backoff, up to
               max_retries consecutive failures.
    stall_timeout_s: producer-stall watchdog on the CONSUMER side — a
               `next()` that waits longer than this emits a loud
               `feeder_stall` event (queue depth attached) and keeps
               waiting, instead of blocking the training loop
               silently.
    event_log: an observe.RunEventLog for the feeder_* /
               feed_quarantined events (stderr otherwise).
    """

    def __init__(self, reader: Callable[[], Iterable[Dict[str, np.ndarray]]],
                 capacity: int = 2, device=None, validate: bool = False,
                 retryable: Optional[Tuple[type, ...]] = None,
                 max_retries: int = 3, backoff_s: float = 0.05,
                 stall_timeout_s: Optional[float] = None,
                 event_log=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self._reader = reader
        self._capacity = capacity
        self._device = device
        self._validate = bool(validate)
        self._retryable = (DEFAULT_RETRYABLE if retryable is None
                           else tuple(retryable))
        self._max_retries = int(max_retries)
        self._backoff_s = float(backoff_s)
        self._stall_timeout_s = stall_timeout_s
        self._event_log = event_log
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._signature: Optional[Dict[str, Tuple[str, int]]] = None
        self.quarantined = 0   # admission-rejected batches (validate)
        self.retries = 0       # transient producer errors survived
        self.stalls = 0        # feeder_stall events emitted

    def _emit(self, kind: str, **fields):
        if self._event_log is not None:
            self._event_log.event(kind, **fields)
        else:
            print(f"DeviceFeeder {kind}: "
                  + " ".join(f"{k}={v}" for k, v in fields.items()),
                  file=sys.stderr)

    # -- lifecycle (py_reader start/reset parity) -----------------------
    def start(self):
        """Begin prefetching a fresh pass over the reader."""
        self.reset()
        # a fresh pass must not serve the previous pass's cached
        # speed-test batch
        if hasattr(self, "_speed_test_batch"):
            del self._speed_test_batch
        self._queue = queue.Queue(maxsize=self._capacity)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._producer, args=(self._queue,), daemon=True)
        self._thread.start()
        return self

    def reset(self):
        """Stop the current pass (reference py_reader.reset).  The
        producer owns its queue reference, so a slow reader that outlives
        the join timeout dies quietly on the stop flag instead of
        crashing on a nulled queue."""
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
        self._thread = None
        self._queue = None
        if hasattr(self, "_speed_test_batch"):
            del self._speed_test_batch

    # -- producer -------------------------------------------------------
    def _put(self, q: queue.Queue, item) -> bool:
        """Blocking put that aborts when reset() raises the stop flag."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _reopen(self, produced: int):
        """Recover a transient producer failure: re-open the reader
        and fast-forward past the batches already handed downstream —
        the deterministic-reader contract checkpoint resume already
        imposes makes the replayed prefix identical."""
        it = iter(self._reader())
        for _ in range(produced):
            next(it)
        return it

    def _producer(self, q: queue.Queue):
        import jax

        from ..resilience import chaos

        produced = 0     # batches handed to the queue this pass
        attempts = 0     # consecutive transient failures
        try:
            it = iter(self._reader())
            while not self._stop.is_set():
                try:
                    # deterministic fault injection for the retry and
                    # stall-watchdog proofs (tests + CI chaos smoke)
                    chaos.delaypoint("feeder:producer")
                    chaos.failpoint("feeder:producer")
                    batch = next(it)
                except StopIteration:
                    self._put(q, _STOP)
                    return
                except self._retryable as e:
                    attempts += 1
                    if attempts > self._max_retries:
                        raise
                    self.retries += 1
                    self._emit("feeder_retry", attempt=attempts,
                               max_retries=self._max_retries,
                               produced=produced,
                               error=f"{type(e).__name__}: {e}")
                    time.sleep(self._backoff_s * (2 ** (attempts - 1)))
                    it = self._reopen(produced)
                    continue
                attempts = 0
                if self._validate:
                    problems = validate_feed_batch(batch,
                                                   self._signature)
                    if problems:
                        self.quarantined += 1
                        self._emit("feed_quarantined",
                                   produced=produced,
                                   quarantined=self.quarantined,
                                   problems=problems)
                        continue
                    if self._signature is None:
                        self._signature = feed_signature(batch)
                # device_put is async: the transfer starts now and
                # overlaps the consumer's current step
                # (buffered_reader.cc's pinned-mem copy)
                placed = {n: jax.device_put(v, self._device)
                          for n, v in batch.items()}
                if not self._put(q, placed):
                    return
                produced += 1
        except BaseException as e:  # surfaced on the consumer side
            self._put(q, _ReaderError(e))

    # -- consumer -------------------------------------------------------
    def __iter__(self):
        if self._queue is None:
            self.start()
        return self

    def _get(self):
        """Queue pop with the producer-stall watchdog: waiting past
        stall_timeout_s emits a loud `feeder_stall` (queue depth +
        cumulative wait attached) and keeps waiting — the starved
        consumer is diagnosable without killing the pass."""
        if not self._stall_timeout_s:
            return self._queue.get()
        waited = 0.0
        while True:
            try:
                return self._queue.get(timeout=self._stall_timeout_s)
            except queue.Empty:
                waited += self._stall_timeout_s
                self.stalls += 1
                self._emit("feeder_stall",
                           queue_depth=self._queue.qsize(),
                           capacity=self._capacity,
                           waited_s=round(waited, 3),
                           producer_alive=(
                               self._thread is not None
                               and self._thread.is_alive()))

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._queue is None:
            raise StopIteration
        from ..flags import FLAGS

        if FLAGS.reader_queue_speed_test_mode:
            # non-destructive mode (reference
            # FLAGS_reader_queue_speed_test_mode): serve the first batch
            # forever so consumer-side throughput excludes producer cost
            if not hasattr(self, "_speed_test_batch"):
                self._speed_test_batch = self._get()
            if self._speed_test_batch is _STOP or isinstance(
                    self._speed_test_batch, _ReaderError):
                item = self._speed_test_batch
            else:
                return self._speed_test_batch
        else:
            item = self._get()
        if item is _STOP:
            self._queue = None
            self._thread = None
            raise StopIteration
        if isinstance(item, _ReaderError):
            self._queue = None
            raise item.error
        return item


class PyReader:
    """fluid-style py_reader facade (reference layers/io.py:633): declare
    feed vars once, decorate with a sample/batch reader, iterate
    device-resident batches.

        reader = PyReader(feed_list=[img, label], capacity=4)
        reader.decorate_batch_generator(my_batches)
        for feed in reader:
            exe.run(main, feed=feed, fetch_list=[loss])
    """

    def __init__(self, feed_list: Sequence, capacity: int = 2):
        self._names: List[str] = []
        for v in feed_list:
            name = v if isinstance(v, str) else v.name
            self._names.append(name)
            # sequence inputs (lod_level > 0) need their .seq_len
            # companion fed too: expect it as the next tuple slot
            # (mirrors DataFeeder, data/data_feeder.py)
            if (not isinstance(v, str)
                    and getattr(v.desc, "lod_level", 0) > 0):
                self._names.append(f"{name}.seq_len")
        self._capacity = capacity
        self._feeder: Optional[DeviceFeeder] = None
        self._gen = None

    def decorate_batch_generator(self, generator):
        """generator: callable -> iterable of tuples/lists/dicts of numpy
        batches aligned with feed_list."""
        names = self._names

        def reader():
            for item in generator():
                if isinstance(item, dict):
                    yield item
                else:
                    if len(item) != len(names):
                        raise ValueError(
                            f"batch has {len(item)} arrays for "
                            f"{len(names)} feed vars {names}")
                    yield dict(zip(names, item))

        self._gen = reader
        return self

    decorate_paddle_reader = decorate_batch_generator

    def start(self):
        if self._gen is None:
            raise RuntimeError("decorate_batch_generator first")
        self._feeder = DeviceFeeder(self._gen, capacity=self._capacity)
        self._feeder.start()
        return self

    def reset(self):
        if self._feeder is not None:
            self._feeder.reset()
            self._feeder = None

    def __iter__(self):
        if self._feeder is None:
            self.start()
        return iter(self._feeder)
