"""Per-request distributed tracing — observe pillar 7 (request side).

Aggregate percentiles answer "how slow is the service"; they cannot
answer "why was THIS request slow" — under continuous batching the
interesting pathologies are per-request (a mid-stream join that waited
three chunks for pages, a preemption, a failover hop to another
replica) and vanish into a p99.  This module is the host-side tracer
the serving stack threads a `RequestTrace` through:

- **spans are host timestamps at queue boundaries only** — submit,
  slot/batch admission, dispatch enqueue/return, failover detection.
  Nothing here touches the device: zero extra dispatches, zero
  retraces, byte-identical step lowering whether tracing is on or off
  (pinned by tests/test_observe_reqtrace.py, the ISSUE 4/PR 11 guard
  discipline).  A span is ~a tuple append; the cost of tracing every
  request is microseconds of host time per request.
- **head sampling + tail-based keep** — `sample_rate` head-samples the
  normal traffic (deterministic 1-in-round(1/rate)), but every trace
  is RECORDED until it finishes and is force-kept when it turns out to
  matter: an error, a failover/hedge/preemption marker, or an
  end-to-end time over `slow_keep_ms`.  The pathological tail is never
  sampled away; `sample_rate=0` keeps exactly the pathologies.
- **bounded memory** — kept traces land in a ring (`capacity`); spans
  per trace are capped (`max_spans`, drops counted, never unbounded).
- **exact phase aggregation regardless of sampling** — every finished
  trace folds its span durations into per-phase `LatencyHistogram`s
  (`phase_summary()`), so bench.py's queue_wait/batch_form/dispatch/
  join_wait percentiles are computed over ALL requests even at
  sample_rate=0.
- **one timeline under chaos** — `export_chrome_trace()` renders the
  kept window as a chrome://tracing / Perfetto JSON: rows (pids) are
  replicas (the router is its own row), one line per trace, so a
  request that failed over draws queue -> dispatch -> failover-hop ->
  completion ACROSS replica rows.

Span taxonomy (docs/OBSERVE.md pillar 7): single-shot serving uses
`queue_wait` / `batch_form` / `dispatch`; decode uses `join_wait` /
`dispatch`(kind=prefill|decode, one per chunk) plus `preempt` /
`evacuated` point markers; the fleet router adds `route`, `failover`
(from_replica/to_replica), `hedge`, `abandoned` (the hedge loser) and
`complete`.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from .monitoring import LatencyHistogram

# a span/point with one of these names force-keeps its trace at
# finish(): these are exactly the per-request pathologies aggregate
# percentiles hide
TAIL_KEEP_MARKS = ("failover", "hedge", "abandoned", "preempt",
                   "evacuated")


def new_trace_id() -> str:
    """16 hex chars, unique per request (not per attempt: the id is
    what ties a failover's hops together)."""
    return uuid.uuid4().hex[:16]


class Span:
    """One phase of one request: a named [t0, t1) host interval with
    attributes (replica_id/slot/bucket/...).  Timestamps are
    time.monotonic() seconds; durations are exact, absolute times are
    only comparable within one process."""

    __slots__ = ("name", "t0", "t1", "attrs")

    def __init__(self, name: str, t0: float, t1: float,
                 attrs: Dict[str, Any]):
        self.name = name
        self.t0 = float(t0)
        self.t1 = float(t1)
        self.attrs = attrs

    @property
    def duration_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3

    def as_dict(self) -> Dict[str, Any]:
        out = {"name": self.name, "t0": round(self.t0, 6),
               "dur_ms": round(self.duration_ms, 3)}
        if self.attrs:
            out.update(self.attrs)
        return out

    def __repr__(self):
        return (f"Span({self.name!r}, {self.duration_ms:.3f}ms, "
                f"{self.attrs})")


class RequestTrace:
    """Host-side trace of one logical request across replicas.

    Thread-safe append-only: the submit thread, batcher/scheduler
    threads, and fleet callbacks all add spans to the same trace.  The
    trace object itself travels with the request (a field on the
    engine-side Request / the router-side _FleetRequest), so no
    context-propagation machinery is needed — the repo is one process.
    """

    __slots__ = ("trace_id", "kind", "t_create", "t_finish", "spans",
                 "head_sampled", "finished", "kept", "keep_reason",
                 "error", "dropped_spans", "fleet_owned", "_max_spans",
                 "_lock")

    def __init__(self, kind: str = "request", head_sampled: bool = True,
                 max_spans: int = 512,
                 trace_id: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.kind = kind
        self.t_create = time.monotonic()
        self.t_finish: Optional[float] = None
        self.spans: List[Span] = []
        self.head_sampled = bool(head_sampled)
        self.finished = False
        self.kept = False
        self.keep_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.dropped_spans = 0
        self.fleet_owned = False   # the router finishes it, engines
        #                            only add spans
        self._max_spans = int(max_spans)
        self._lock = threading.Lock()

    # -- recording ------------------------------------------------------
    def add(self, name: str, t0: float, t1: float, **attrs: Any
            ) -> Optional[Span]:
        """Record one completed phase from explicit monotonic
        timestamps (the engines know their queue-boundary stamps
        already — e.g. Request.t_submit — so spans are added
        retroactively in one call, no begin/end pairing across
        threads)."""
        sp = Span(name, t0, t1, attrs)
        with self._lock:
            if len(self.spans) >= self._max_spans:
                self.dropped_spans += 1
                return None
            self.spans.append(sp)
        return sp

    def point(self, name: str, **attrs: Any) -> Optional[Span]:
        """Instantaneous marker (preempt / hedge / abandoned ...)."""
        now = time.monotonic()
        return self.add(name, now, now, **attrs)

    # -- reading --------------------------------------------------------
    @property
    def duration_ms(self) -> float:
        end = self.t_finish if self.t_finish is not None \
            else time.monotonic()
        return (end - self.t_create) * 1e3

    def span_names(self) -> List[str]:
        with self._lock:
            return [s.name for s in self.spans]

    def find(self, name: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def has(self, name: str) -> bool:
        with self._lock:
            return any(s.name == name for s in self.spans)

    def replica_ids(self) -> List[int]:
        """Distinct replica_id attrs across spans, in first-seen order
        — the hop chain a chrome export renders as rows."""
        seen: List[int] = []
        with self._lock:
            for s in self.spans:
                r = s.attrs.get("replica_id")
                if r is not None and r not in seen:
                    seen.append(r)
        return seen

    def phase_ms(self) -> Dict[str, float]:
        """Total milliseconds per span name (the per-request phase
        breakdown)."""
        out: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = out.get(s.name, 0.0) + s.duration_ms
        return {k: round(v, 3) for k, v in out.items()}

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            spans = [s.as_dict() for s in self.spans]
        return {"trace_id": self.trace_id, "kind": self.kind,
                "duration_ms": round(self.duration_ms, 3),
                "error": self.error, "kept": self.kept,
                "keep_reason": self.keep_reason,
                "dropped_spans": self.dropped_spans,
                "spans": spans}

    def __repr__(self):
        return (f"RequestTrace({self.trace_id}, {self.kind}, "
                f"{len(self.spans)} spans, "
                f"{self.duration_ms:.1f}ms)")


class ReqTracer:
    """The per-request tracing plane one serving component owns (a
    Fleet, or a directly-used engine).

        tracer = ReqTracer(sample_rate=0.01, slow_keep_ms=500)
        fleet = Fleet(engines, config, tracer=tracer)
        ...
        tracer.phase_summary()       # exact percentiles per phase
        tracer.export_chrome_trace("trace.json", window_s=60)

    sample_rate: head-sampling fraction of NORMAL traces kept
        (deterministic: every round(1/rate)-th).  0 keeps only the
        tail (slow/error/failover/...); 1 keeps everything.
    slow_keep_ms: tail-keep any trace slower end-to-end than this
        (None disables the latency criterion).
    capacity: kept-trace ring bound (oldest evicted).
    max_spans: per-trace span cap (chunked decode generates one
        dispatch span per chunk; a 10k-token generation must not
        grow without bound).
    """

    def __init__(self, sample_rate: float = 1.0, capacity: int = 512,
                 slow_keep_ms: Optional[float] = None,
                 max_spans: int = 512):
        if not (0.0 <= sample_rate <= 1.0):
            raise ValueError("sample_rate must be in [0, 1]")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if slow_keep_ms is not None and slow_keep_ms <= 0:
            raise ValueError("slow_keep_ms must be > 0")
        self.sample_rate = float(sample_rate)
        self.capacity = int(capacity)
        self.slow_keep_ms = slow_keep_ms
        self.max_spans = int(max_spans)
        self._ring: deque = deque(maxlen=self.capacity)
        self._phase_hists: Dict[str, LatencyHistogram] = {}
        self._lock = threading.Lock()
        self._seq = 0
        # lifetime counters (the reqtrace_* metrics family)
        self.started = 0
        self.finished = 0
        self.kept = 0
        self.tail_kept = 0     # kept ONLY because of a tail criterion
        self.errors = 0

    # -- trace lifecycle ------------------------------------------------
    def _head_sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        period = max(1, int(round(1.0 / self.sample_rate)))
        return self._seq % period == 0

    def new_trace(self, kind: str = "request") -> RequestTrace:
        with self._lock:
            head = self._head_sample()
            self._seq += 1
            self.started += 1
        return RequestTrace(kind=kind, head_sampled=head,
                            max_spans=self.max_spans)

    def finish(self, trace: RequestTrace,
               error: Optional[BaseException] = None) -> bool:
        """Close one trace: stamp the end, fold span durations into
        the exact per-phase histograms, decide keep (head sample OR
        tail criteria) and ring it.  Idempotent — a failover path may
        race a late engine resolution; the first finish wins."""
        with trace._lock:
            if trace.finished:
                return trace.kept
            trace.finished = True
            trace.t_finish = time.monotonic()
            if error is not None:
                trace.error = f"{type(error).__name__}: {error}"
            spans = list(trace.spans)
        marks = [s.name for s in spans if s.name in TAIL_KEEP_MARKS]
        reason = None
        if trace.error is not None:
            reason = "error"
        elif marks:
            reason = marks[0]
        elif (self.slow_keep_ms is not None
              and trace.duration_ms >= self.slow_keep_ms):
            reason = "slow"
        keep = trace.head_sampled or reason is not None
        trace.kept = keep
        trace.keep_reason = reason if reason is not None else (
            "head_sampled" if keep else None)
        with self._lock:
            self.finished += 1
            if trace.error is not None:
                self.errors += 1
            for s in spans:
                h = self._phase_hists.get(s.name)
                if h is None:
                    h = self._phase_hists[s.name] = LatencyHistogram()
                h.record(s.duration_ms)
            if keep:
                self.kept += 1
                if reason is not None and not trace.head_sampled:
                    self.tail_kept += 1
                self._ring.append(trace)
        return keep

    # -- reading --------------------------------------------------------
    def traces(self, window_s: Optional[float] = None
               ) -> List[RequestTrace]:
        """Kept traces, oldest first; `window_s` restricts to traces
        finished within the last window_s seconds."""
        with self._lock:
            out = list(self._ring)
        if window_s is not None:
            cut = time.monotonic() - window_s
            out = [t for t in out
                   if t.t_finish is not None and t.t_finish >= cut]
        return out

    def trace(self, trace_id: str) -> Optional[RequestTrace]:
        with self._lock:
            for t in self._ring:
                if t.trace_id == trace_id:
                    return t
        return None

    def phase_summary(self) -> Dict[str, Dict[str, Any]]:
        """{phase: LatencyHistogram.summary()} over EVERY finished
        trace (sampling only affects which traces are retained whole,
        never these aggregates)."""
        with self._lock:
            hists = dict(self._phase_hists)
        return {name: h.summary() for name, h in sorted(hists.items())}

    def phase_histograms(self) -> Dict[str, LatencyHistogram]:
        """The live per-phase histograms (the metrics registry's
        histogram source; treat as read-only)."""
        with self._lock:
            return dict(self._phase_hists)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"started": self.started, "finished": self.finished,
                    "kept": self.kept, "tail_kept": self.tail_kept,
                    "errors": self.errors,
                    "ring_size": len(self._ring),
                    "capacity": self.capacity,
                    "sample_rate": self.sample_rate}

    # -- chrome trace export --------------------------------------------
    def export_chrome_trace(self, path: Optional[str] = None,
                            window_s: Optional[float] = None
                            ) -> Dict[str, Any]:
        """Render the kept window as a chrome://tracing JSON.

        Rows: pid = replica (span attr `replica_id`; spans without one
        — the router's route/failover bookkeeping — land on the
        "router" row), tid = one line per trace within its replica row,
        so concurrent requests stack instead of overlapping.  A
        failed-over request's single trace_id therefore draws its
        queue/dispatch spans on replica A's row, the failover hop, and
        the completion spans on replica B's row — one timeline for a
        ragged stream under chaos.  Timestamps are µs relative to the
        oldest exported trace.

        Disagg handoffs additionally render as chrome FLOW events: a
        `kv_transfer` span (router row; from_replica/to_replica attrs)
        emits an `s`/`f` arrow pair from the prefill worker's row to
        the decode worker's row, so one trace_id draws
        prefill-row → transfer arrow → decode-row."""
        traces = self.traces(window_s)
        events: List[Dict[str, Any]] = []
        if not traces:
            out = {"traceEvents": [], "displayTimeUnit": "ms"}
            if path:
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(out, f)
            return out
        base = min(t.t_create for t in traces)
        ROUTER_PID = 0
        pids = {None: ROUTER_PID}

        def pid_of(replica_id):
            if replica_id not in pids:
                pids[replica_id] = int(replica_id) + 1
            return pids[replica_id]

        flow_id = 0
        for tid, t in enumerate(traces, start=1):
            with t._lock:
                spans = list(t.spans)
            for s in spans:
                ev: Dict[str, Any] = {
                    "name": s.name, "ph": "X", "cat": t.kind,
                    "ts": round((s.t0 - base) * 1e6, 1),
                    "dur": max(round((s.t1 - s.t0) * 1e6, 1), 1.0),
                    "pid": pid_of(s.attrs.get("replica_id")),
                    "tid": tid,
                    "args": {"trace_id": t.trace_id, **s.attrs},
                }
                if t.error:
                    ev["args"]["trace_error"] = t.error
                events.append(ev)
                if s.name == "kv_transfer" \
                        and s.attrs.get("from_replica") is not None \
                        and s.attrs.get("to_replica") is not None:
                    # the handoff arrow: flow start on the prefill
                    # worker's row, flow finish on the decode
                    # worker's row, tied by a shared id
                    flow_id += 1
                    common = {"name": "kv_transfer",
                              "cat": "kv_transfer", "tid": tid,
                              "id": flow_id,
                              "args": {"trace_id": t.trace_id}}
                    events.append({
                        **common, "ph": "s",
                        "ts": round((s.t0 - base) * 1e6, 1),
                        "pid": pid_of(s.attrs["from_replica"])})
                    events.append({
                        **common, "ph": "f", "bp": "e",
                        "ts": round((s.t1 - base) * 1e6, 1),
                        "pid": pid_of(s.attrs["to_replica"])})
        for replica_id, pid in pids.items():
            name = ("router" if replica_id is None
                    else f"replica {replica_id}")
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "args": {"name": name}})
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(out, f)
        return out
