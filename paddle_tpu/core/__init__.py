"""Core IR + executor (analog of paddle/fluid/framework/)."""
