"""HBM memory observability: buffer-level attribution, the peak-memory
timeline, and the pre-compile fit planner (observe pillar 5).

Time (trace.py) and flops/bytes-moved (cost.py) already attribute to
fluid ops; memory was one opaque host-side number
(`observe.peak_memory_bytes()`), even though the three most
consequential recorded decisions of the r05 cycle were MEMORY
decisions: remat on/off at longctx (0.306 vs 0.243 MFU — and the XLA
composition needs remat just to fit), dense-at-8k "cannot compile at
all", and the serving bucket ladder sized by guesswork.  This module
makes HBM a first-class observed quantity:

- **buffer attribution** (`memory_report` / `memory_table` /
  `format_memory_table`): parse the optimized module's
  BufferAssignmentProto — `compiled.memory_analysis()` hands back an
  HloProto whose field 3 carries it, read with the same dependency-free
  wire scanner as trace/cost — and attribute every logical buffer to
  its fluid op through the `metadata.op_name` scope join cost.py
  already uses.  Peak = the sum of allocation sizes: XLA's heap
  simulation has ALREADY packed temp buffers into arenas with
  liveness-based reuse, so the allocation total IS what the device
  must hold (cross-checked against CompiledMemoryStats
  args+outputs+temps-aliased within 0.1% on CPU).  Without a buffer
  assignment (backend doesn't expose one) the report falls back to a
  live-range sweep over the instruction sequence from our own proto
  walk, tagged `source: "module-shapes"`.

- **buckets**: every buffer lands in params / optimizer_state /
  gradients / activations / workspace, with donated bytes tallied
  across buckets.  Entry parameters classify by NAME — the executor's
  step is `fn(state, feeds)` and the flattened pytree leaf order is
  the HLO entry parameter order, so parameter_number → state var name
  (`Executor.compiled_step(with_names=True)` plumbs the names).
  Instruction-defined buffers classify by scope: `transpose(jvp(...))`
  wrappers are the AD backward (gradients), optimizer op types are
  update math (optimizer_state), other attributed scopes are forward
  activations, unattributed temps are workspace.

- **timeline** (`memory_timeline` / `export_chrome_trace`): cumulative
  live bytes over the entry instruction schedule, built from the
  assignment's (allocation, offset) slots so XLA's buffer reuse is
  respected — "what is alive at the peak" is a one-call answer, and
  the curve exports as chrome-trace counter events next to the
  RunEventLog.

- **fit planner** (`plan_fit`): predict peak HBM for a candidate
  (batch, seq, dtype, remat) configuration WITHOUT compiling it.
  Peak memory of these step programs is affine in batch (params and
  optimizer state are constant; activations, gradients, and feeds
  scale per-example), so the planner compiles the SAME program at two
  small probe batches — cheap, CPU-safe, never touching the candidate
  shape — and extrapolates the affine fit to the candidate.  Dev
  validation on CPU: within 1% at 16x extrapolation on both headline
  models; `PLAN_FIT_REL_TOL` records the asserted bound (10%).  A
  static fusion-model estimator over the unoptimized module was
  validated first and REJECTED: its error spanned 0.8x-1.4x across
  models because XLA's fusion/layout decisions (inlined calls,
  materialized concats, layout copies) are not predictable pre-compile
  — and the measured arena itself moves ~15% with parameter name
  ordering, so only a same-program probe can stay inside 10%.

CPU-vs-TPU caveat (docs/OBSERVE.md): CPU `memory_analysis` numbers
bound the program's buffer structure but do not equal v5e HBM —
layout/padding and fusion differ per backend.  Chip-free planning is
for RELATIVE decisions (ladder sizing, remat A/Bs, batch scaling); an
absolute fit verdict against `DEVICE_HBM_BYTES` is a prediction whose
accuracy band is only recorded for same-backend probes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .cost import HloModule, _varints
from .trace import _fields, _first, fluid_op_of

# --------------------------------------------------------------------------
# device HBM budgets (planning denominators; memory_stats()["bytes_limit"]
# is the live source on a real chip — device_memory_budget())
# --------------------------------------------------------------------------

DEVICE_HBM_BYTES = {
    "TPU v4": 32_000_000_000,
    "TPU v5 lite": 16_000_000_000,
    "TPU v5e": 16_000_000_000,
    "TPU v5p": 95_000_000_000,
    "TPU v5": 95_000_000_000,
    "TPU v6 lite": 32_000_000_000,
    "TPU v6e": 32_000_000_000,
}

# optimizer op types (ops/optim.py registrations): instructions scoped
# to these are update math, and their non-Param/Grad operands name the
# resident optimizer-state vars
OPTIMIZER_OP_TYPES = {
    "sgd", "momentum", "lars_momentum", "adam", "adamax", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "proximal_gd",
    "proximal_adagrad", "average_accumulates", "ema_accumulate",
}

BUCKETS = ("params", "optimizer_state", "gradients", "activations",
           "workspace")

# plan_fit's recorded accuracy bound vs the proto-derived measurement
# on the SAME backend (asserted by tests/test_observe_memory.py and the
# run_ci.sh memory smoke; dev validation measured <1% at 16x batch
# extrapolation on the resnet/transformer test configs)
PLAN_FIT_REL_TOL = 0.10


def device_memory_budget(device=None) -> Optional[int]:
    """The device allocator's byte limit (`memory_stats()["bytes_limit"]`),
    falling back to the DEVICE_HBM_BYTES table by device kind; None when
    neither reports (the CPU test backend) — callers must treat None as
    "no budget known", never assume a default chip."""
    from .monitoring import device_memory_stats

    stats = device_memory_stats(device)
    if "bytes_limit" in stats:
        return int(stats["bytes_limit"])
    import jax

    kind = (device if device is not None
            else jax.local_devices()[0]).device_kind
    for prefix, cap in DEVICE_HBM_BYTES.items():
        if kind.startswith(prefix):
            return cap
    return None


# --------------------------------------------------------------------------
# BufferAssignmentProto parsing (xla/service/hlo.proto, stable numbers)
# --------------------------------------------------------------------------

# HloProto:              hlo_module=1 buffer_assignment=3
# BufferAssignmentProto: logical_buffers=1 buffer_aliases=2
#                        buffer_allocations=3 heap_simulator_traces=4
# LogicalBufferProto:    id=1 size=2 defined_at=3
#   .Location:           shape_index=3 instruction_id=4
# BufferAllocationProto: index=1 size=2 is_thread_local=3
#                        is_entry_computation_parameter=5
#                        parameter_number=6 maybe_live_out=7 color=8
#                        assigned=9 is_tuple=11 is_constant=12
#   .Assigned:           logical_buffer_id=1 offset=2 size=3


class LogicalBuffer:
    __slots__ = ("id", "size", "instr_id", "shape_index")

    def __init__(self, buf: bytes):
        self.id = 0
        self.size = 0
        self.instr_id: Optional[int] = None
        self.shape_index: List[int] = []
        for f, _wt, v in _fields(buf):
            if f == 1:
                self.id = v
            elif f == 2:
                self.size = v
            elif f == 3:
                for lf, _lwt, lv in _fields(v):
                    if lf == 4:
                        self.instr_id = lv
                    elif lf == 3:
                        self.shape_index = _varints(lv)


class Allocation:
    __slots__ = ("index", "size", "is_param", "param_number", "live_out",
                 "is_constant", "is_tuple", "is_thread_local", "assigned")

    def __init__(self, buf: bytes):
        self.index = 0
        self.size = 0
        self.is_param = False
        self.param_number: Optional[int] = None
        self.live_out = False
        self.is_constant = False
        self.is_tuple = False
        self.is_thread_local = False
        self.assigned: List[Tuple[int, int, int]] = []  # (buf_id, off, sz)
        for f, _wt, v in _fields(buf):
            if f == 1:
                self.index = v
            elif f == 2:
                self.size = v
            elif f == 3:
                self.is_thread_local = bool(v)
            elif f == 5:
                self.is_param = bool(v)
            elif f == 6:
                self.param_number = v
            elif f == 7:
                self.live_out = bool(v)
            elif f == 11:
                self.is_tuple = bool(v)
            elif f == 12:
                self.is_constant = bool(v)
            elif f == 9:
                bid = off = sz = 0
                for af, _awt, av in _fields(v):
                    if af == 1:
                        bid = av
                    elif af == 2:
                        off = av
                    elif af == 3:
                        sz = av
                self.assigned.append((bid, off, sz))


class BufferAssignment:
    def __init__(self, buf: bytes):
        self.buffers: Dict[int, LogicalBuffer] = {}
        self.allocations: List[Allocation] = []
        for f, _wt, v in _fields(buf):
            if f == 1:
                lb = LogicalBuffer(v)
                self.buffers[lb.id] = lb
            elif f == 3:
                self.allocations.append(Allocation(v))

    @property
    def total_bytes(self) -> int:
        """Peak device memory: the sum of allocation sizes.  XLA's heap
        simulation already packed temp buffers into arenas with
        liveness-based reuse, and a donated (param AND live-out)
        allocation appears ONCE — this total is what the device must
        actually hold."""
        return int(sum(a.size for a in self.allocations))


def parse_buffer_assignment(proto: bytes) -> Optional[BufferAssignment]:
    """BufferAssignment of an HloProto wrapper (field 3), or None when
    the proto is a bare module / carries no assignment."""
    ba = _first(proto, 3)
    if not isinstance(ba, bytes) or not ba:
        return None
    parsed = BufferAssignment(ba)
    if not parsed.allocations:
        return None
    return parsed


def compiled_memory_proto(compiled) -> Tuple[bytes, Optional[Any]]:
    """(proto, CompiledMemoryStats|None) for a jax Compiled object.
    Prefers memory_analysis() — its serialized HloProto carries the
    buffer assignment — and falls back to the bare optimized module
    (attribution still works; peak comes from a live-range sweep)."""
    try:
        stats = compiled.memory_analysis()
        if isinstance(stats, (list, tuple)):
            stats = stats[0]
        proto = stats.serialized_hlo_proto
        if isinstance(proto, bytes) and proto:
            return proto, stats
    except Exception:  # noqa: BLE001 — backend-dependent API
        pass
    from .cost import compiled_hlo_proto

    return compiled_hlo_proto(compiled), None


def compiled_peak_bytes(compiled) -> Optional[int]:
    """Predicted-peak device bytes of one compiled executable: the
    buffer-assignment allocation total, falling back to the
    CompiledMemoryStats arithmetic, else None (backend reports
    nothing)."""
    try:
        stats = compiled.memory_analysis()
        if isinstance(stats, (list, tuple)):
            stats = stats[0]
    except Exception:  # noqa: BLE001
        return None
    proto = getattr(stats, "serialized_hlo_proto", None)
    if isinstance(proto, bytes) and proto:
        ba = parse_buffer_assignment(proto)
        if ba is not None:
            return ba.total_bytes
    try:
        return int(stats.argument_size_in_bytes
                   + stats.output_size_in_bytes
                   + stats.temp_size_in_bytes
                   - stats.alias_size_in_bytes)
    except Exception:  # noqa: BLE001
        return None


# --------------------------------------------------------------------------
# classification
# --------------------------------------------------------------------------

def _program_var_buckets(program) -> Tuple[set, set]:
    """(param_names, optimizer_state_names) from the program desc.
    Optimizer state = the non-Param/Grad operands and outputs of
    optimizer ops (accumulators, pow counters, the lr var) — robust to
    the `<param>.<acc>` naming without parsing names."""
    params, opt = set(), set()
    block = program.global_block()
    for name, var in block.vars.items():
        if getattr(var.desc, "is_parameter", False):
            params.add(name)
    for op in block.ops:
        if op.type not in OPTIMIZER_OP_TYPES:
            continue
        for slot, names in op.desc.inputs.items():
            if slot not in ("Param", "Grad"):
                opt.update(names)
        for slot, names in op.desc.outputs.items():
            if slot != "ParamOut":
                opt.update(names)
    return params, opt - params


def _state_bucket(name: str, params: set, opt: set) -> str:
    from ..core.executor import RNG_STATE_VAR
    from .metrics import TELEMETRY_VAR

    if name in params:
        return "params"
    if name in opt:
        return "optimizer_state"
    if name in (RNG_STATE_VAR, TELEMETRY_VAR):
        return "workspace"
    # other persistable state (BN running stats, custom counters) is
    # model state: it must be resident exactly like params
    return "params"


def _instr_bucket(op_name: str) -> str:
    op_type = fluid_op_of(op_name or "")
    if op_type is None:
        return "workspace"
    if op_type in OPTIMIZER_OP_TYPES:
        return "optimizer_state"
    if "transpose(" in op_name:
        # the executor's AD boundary: backward instructions carry
        # transpose(jvp(<op>:<idx>)) scopes (see trace.py)
        return "gradients"
    return "activations"


def _arg_labels(state, feed_arrays, compiled=None
                ) -> List[Tuple[str, str]]:
    """Flattened (kind, name) per HLO entry parameter, in jax's pytree
    leaf order for fn(state, feeds).  With `compiled`, labels of
    arguments jax PRUNED from the executable (keep_unused=False drops
    unused leaves) are filtered out via the executable's kept-var set —
    otherwise a pruned leaf shifts every later label and memory_report
    falls back to nameless params."""
    import jax.tree_util as jtu

    labels: List[Tuple[str, str]] = []
    for path, _leaf in jtu.tree_flatten_with_path((state, feed_arrays))[0]:
        kind = "state" if path[0].idx == 0 else "feed"
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path[1:])
        labels.append((kind, name))
    if compiled is not None:
        try:  # private API; absence degrades to the nameless fallback
            kept = compiled._executable._kept_var_idx
            labels = [lb for i, lb in enumerate(labels) if i in kept]
        except AttributeError:
            pass
    return labels


# --------------------------------------------------------------------------
# the buffer table
# --------------------------------------------------------------------------

def _module_positions(module: HloModule):
    """(entry, entry position by instruction id, entry position of every
    non-entry computation via its call site — sub-computation buffers
    account at the calling while/fusion/call's schedule position)."""
    entry = module.entry
    pos = {i.id: k for k, i in enumerate(entry.instructions)}
    comp_pos: Dict[int, int] = {}
    pending = [(cid, pos[i.id]) for i in entry.instructions
               for cid in i.called_ids]
    while pending:
        cid, p = pending.pop()
        if cid in comp_pos or cid not in module.computations:
            continue
        comp_pos[cid] = p
        for i in module.computations[cid].instructions:
            for sub in i.called_ids:
                pending.append((sub, p))
    instr_comp: Dict[int, int] = {}
    for cid, comp in module.computations.items():
        for i in comp.instructions:
            instr_comp[i.id] = cid
    return entry, pos, comp_pos, instr_comp


def memory_report(program=None, feed=None, fetch_list=None, scope=None,
                  exe=None, compiled=None, arg_names=None
                  ) -> Dict[str, Any]:
    """Buffer-level memory accounting of a program's optimized step.

    Returns {rows, peak_bytes, breakdown, source, stats}:
    - rows: one per parameter/constant ALLOCATION and one per sized
      temp logical buffer — {bytes, bucket, op_type, opcode,
      instruction, param, donated, live_out, allocation}.  A donated
      parameter is ONE row (the updated value shares its slot).
    - peak_bytes: the allocation total (what the device must hold).
    - breakdown: per-bucket byte sums + "donated" (cross-bucket) +
      "peak_bytes".  params/optimizer_state sums are exact resident
      sizes; temp-bucket sums (activations/gradients/workspace) are
      FOOTPRINT attribution — XLA reuses arena slots over time, so
      their sum may exceed peak_bytes.  Use the timeline for
      concurrently-live truth.
    - source: "buffer_assignment" | "module-shapes" (no assignment
      exposed: rows synthesized from instruction output shapes, peak
      from a live-range sweep — an estimate, tagged as such).
    """
    if compiled is None:
        if program is None:
            raise ValueError("memory_report needs a program or a "
                             "compiled step")
        from ..core.executor import Executor

        exe = exe or Executor()
        compiled, arg_names = exe.compiled_step(
            program, feed=feed, fetch_list=fetch_list, scope=scope,
            with_names=True)
    params, opt = (set(), set())
    if program is not None:
        params, opt = _program_var_buckets(program)

    proto, stats = compiled_memory_proto(compiled)
    ba = parse_buffer_assignment(proto)
    module = HloModule(proto)
    entry, pos, comp_pos, instr_comp = _module_positions(module)
    by_id = {i.id: i for comp in module.computations.values()
             for i in comp.instructions}
    n_entry_params = sum(1 for i in entry.instructions
                         if i.opcode == "parameter")
    # parameter_number -> (kind, name); only trustworthy when jax kept
    # every flattened leaf as an entry parameter (keep_unused pruning
    # breaks the numbering — then params stay nameless, never mislabeled)
    names_ok = arg_names is not None and len(arg_names) == n_entry_params

    rows: List[Dict[str, Any]] = []

    def classify(alloc: Optional[Allocation], instr) -> Tuple[str, Any]:
        if alloc is not None and alloc.is_param:
            if names_ok and alloc.param_number is not None \
                    and alloc.param_number < len(arg_names):
                kind, name = arg_names[alloc.param_number]
                if kind == "feed":
                    return "activations", name
                return _state_bucket(name, params, opt), name
            return "params", None
        if instr is not None and instr.opcode == "parameter" \
                and instr_comp.get(instr.id) != entry.id:
            # sub-computation parameter (loop carry): workspace
            return "workspace", None
        return _instr_bucket(instr.op_name if instr is not None
                             else ""), None

    if ba is not None:
        for a in ba.allocations:
            members = [ba.buffers[bid] for bid, _off, _sz in a.assigned
                       if bid in ba.buffers]
            if a.is_param:
                # one row per parameter allocation: the in-place
                # updated value (donation) shares the slot — two rows
                # would double-count the resident bytes
                lb = next((b for b in members
                           if (i := by_id.get(b.instr_id)) is not None
                           and i.opcode == "parameter"), None)
                instr = by_id.get(lb.instr_id) if lb is not None else None
                bucket, pname = classify(a, instr)
                rows.append({
                    "bytes": int(a.size), "bucket": bucket,
                    "op_type": None, "opcode": "parameter",
                    "instruction": (instr.name if instr is not None
                                    else None),
                    "param": pname,
                    "donated": bool(a.live_out),
                    "live_out": bool(a.live_out),
                    "allocation": a.index,
                })
                continue
            if a.is_constant:
                rows.append({
                    "bytes": int(a.size), "bucket": "workspace",
                    "op_type": None, "opcode": "constant",
                    "instruction": None, "param": None,
                    "donated": False, "live_out": bool(a.live_out),
                    "allocation": a.index,
                })
                continue
            for lb in members:
                if lb.size <= 0:
                    continue
                instr = by_id.get(lb.instr_id)
                bucket, pname = classify(None, instr)
                rows.append({
                    "bytes": int(lb.size),
                    "bucket": bucket,
                    "op_type": (fluid_op_of(instr.op_name)
                                if instr is not None else None),
                    "opcode": (instr.opcode if instr is not None
                               else None),
                    "instruction": (instr.name if instr is not None
                                    else None),
                    "param": pname,
                    "donated": False,
                    "live_out": bool(a.live_out),
                    "allocation": a.index,
                })
        peak = ba.total_bytes
        source = "buffer_assignment"
    else:
        # no assignment exposed: synthesize buffers from entry
        # instruction output shapes; peak = live-range sweep estimate
        for k, instr in enumerate(entry.instructions):
            nbytes = instr.shape.bytes
            if nbytes <= 0:
                continue
            if instr.opcode == "parameter":
                bucket, pname = "params", None
                if names_ok:
                    # entry parameters appear in order in the entry
                    pidx = sum(1 for i in entry.instructions[:k]
                               if i.opcode == "parameter")
                    if pidx < len(arg_names):
                        kind, name = arg_names[pidx]
                        pname = name
                        bucket = ("activations" if kind == "feed"
                                  else _state_bucket(name, params, opt))
                rows.append({"bytes": int(nbytes), "bucket": bucket,
                             "op_type": None, "opcode": "parameter",
                             "instruction": instr.name, "param": pname,
                             "donated": False, "live_out": False,
                             "allocation": None})
                continue
            if instr.opcode in ("constant", "tuple",
                                "get-tuple-element", "bitcast"):
                continue
            rows.append({
                "bytes": int(nbytes),
                "bucket": _instr_bucket(instr.op_name),
                "op_type": fluid_op_of(instr.op_name),
                "opcode": instr.opcode,
                "instruction": instr.name,
                "param": None,
                "donated": False,
                "live_out": instr.id == entry.root_id,
                "allocation": None,
            })
        peak = _sweep_module_shapes(entry)
        source = "module-shapes"

    rows.sort(key=lambda r: -r["bytes"])
    breakdown = {b: 0 for b in BUCKETS}
    donated = 0
    for r in rows:
        breakdown[r["bucket"]] = breakdown.get(r["bucket"], 0) + r["bytes"]
        if r["donated"]:
            donated += r["bytes"]
    breakdown["donated"] = donated
    breakdown["peak_bytes"] = int(peak)
    out = {"rows": rows, "peak_bytes": int(peak),
           "breakdown": breakdown, "source": source}
    if stats is not None:
        out["stats"] = {
            "argument_bytes": int(stats.argument_size_in_bytes),
            "output_bytes": int(stats.output_size_in_bytes),
            "temp_bytes": int(stats.temp_size_in_bytes),
            "alias_bytes": int(stats.alias_size_in_bytes),
        }
    return out


def _sweep_module_shapes(entry) -> int:
    """Live-range peak estimate over a bare module's entry sequence:
    every non-bookkeeping instruction output materializes from its
    definition to its last use (the cost.py materialized-buffers
    model), parameters and the root are resident."""
    n = len(entry.instructions)
    last_use: Dict[int, int] = {}
    for k, i in enumerate(entry.instructions):
        for oid in i.operand_ids:
            last_use[oid] = k
    deltas = [0] * (n + 1)
    always = 0
    for k, i in enumerate(entry.instructions):
        nbytes = i.shape.bytes
        if nbytes <= 0:
            continue
        if i.opcode == "parameter" or i.id == entry.root_id:
            always += nbytes
            continue
        if i.opcode in ("constant", "tuple", "get-tuple-element",
                        "bitcast"):
            continue
        deltas[k] += nbytes
        deltas[last_use.get(i.id, k) + 1] -= nbytes
    live, peak = always, always
    for k in range(n):
        live += deltas[k]
        peak = max(peak, live)
    return peak


def memory_table(program=None, feed=None, fetch_list=None, scope=None,
                 exe=None, compiled=None, top: Optional[int] = None
                 ) -> List[Dict[str, Any]]:
    """The buffer rows of `memory_report`, largest first (top=N
    truncates)."""
    rows = memory_report(program, feed=feed, fetch_list=fetch_list,
                         scope=scope, exe=exe, compiled=compiled)["rows"]
    return rows[:top] if top else rows


def format_memory_table(rows: Sequence[Dict[str, Any]],
                        top: int = 30) -> str:
    """Human-readable top-N buffer report — the memory analog of
    format_cost_table."""
    hdr = (f"{'MB':>10}  {'Bucket':<16}{'Op':<22}{'Opcode':<16}"
           f"{'Param/Instruction':<32}{'Flags'}")
    lines = ["-------> Buffer-level memory attribution <-------", hdr,
             "-" * len(hdr)]
    for r in rows[:top]:
        flags = []
        if r.get("donated"):
            flags.append("donated")
        if r.get("live_out"):
            flags.append("live-out")
        who = r.get("param") or r.get("instruction") or "?"
        lines.append(
            f"{r['bytes'] / 1e6:>10.3f}  {r['bucket']:<16}"
            f"{(r.get('op_type') or '-'):<22}"
            f"{(r.get('opcode') or '-'):<16}{who:<32}"
            f"{','.join(flags)}")
    if len(rows) > top:
        rest = sum(r["bytes"] for r in rows[top:])
        lines.append(f"... ({len(rows) - top} more buffers, "
                     f"{rest / 1e6:.3f} MB)")
    return "\n".join(lines)


def sharded_memory_report(program, feed=None, fetch_list=None,
                          scope=None) -> Dict[str, Any]:
    """memory_report of the SHARDED (post-SPMD) step: buffer
    accounting of one device's partition of the CompiledProgram
    executable — `breakdown["optimizer_state"]` here is the PER-DEVICE
    resident opt-state bytes, the number the fsdp/ZeRO A/B claims
    drops ~1/N (ISSUE 13).  Requires the program to carry a
    CompiledProgram wrapper (with_data_parallel)."""
    wrapper = getattr(program, "_compiled_wrapper", None)
    if wrapper is None:
        raise ValueError("sharded_memory_report needs a program "
                         "compiled with CompiledProgram"
                         ".with_data_parallel")
    names = [f.name if hasattr(f, "name") else str(f)
             for f in (fetch_list or [])]
    compiled, arg_names = wrapper.compiled_step(
        dict(feed or {}), names, scope, with_names=True)
    return memory_report(program=program, compiled=compiled,
                         arg_names=arg_names)


def resident_state_bytes(report: Dict[str, Any],
                         bucket: str = "optimizer_state") -> int:
    """Resident bytes of a bucket's ENTRY-PARAMETER allocations in a
    memory_report — the arrays that must live in HBM for the whole
    step (accumulators, params), EXCLUDING scope-attributed temps
    (e.g. the pre-all-gather updated-param shard the ZeRO update
    materializes inside the adam scope).  This is the
    `opt_state_bytes_per_device` number the fsdp A/B tracks: on a
    sharded compile it is exactly the per-device accumulator
    footprint, 1/N under ZeRO."""
    return sum(r["bytes"] for r in report["rows"]
               if r["bucket"] == bucket and r["opcode"] == "parameter")


def step_mem_breakdown(program=None, feed=None, fetch_list=None,
                       scope=None, exe=None) -> Dict[str, Any]:
    """The one-dict summary bench.py entries carry: per-bucket byte
    sums + peak_bytes + source.  A program compiled over a REAL
    (multi-device) mesh reports its SHARDED step's per-device buffer
    assignment — the number that must fit each chip — instead of the
    unsharded single-device twin's."""
    wrapper = getattr(program, "_compiled_wrapper", None)
    if wrapper is not None and wrapper._mesh is not None \
            and wrapper._mesh.devices.size > 1:
        rep = sharded_memory_report(program, feed=feed,
                                    fetch_list=fetch_list, scope=scope)
    else:
        rep = memory_report(program, feed=feed, fetch_list=fetch_list,
                            scope=scope, exe=exe)
    out = dict(rep["breakdown"])
    out["source"] = rep["source"]
    return out


# --------------------------------------------------------------------------
# the peak-memory timeline
# --------------------------------------------------------------------------

def memory_timeline(program=None, feed=None, fetch_list=None, scope=None,
                    exe=None, compiled=None) -> Dict[str, Any]:
    """Cumulative live bytes over the entry instruction schedule.

    Built from the buffer assignment's (allocation, offset) slots:
    logical buffers XLA assigned to overlapping offsets of one
    allocation share one physical slot (in-place reuse), so the curve
    reflects the memory the schedule actually occupies — its peak can
    only be ≤ `peak_bytes` (arena packing holds the gap).

    Returns {points, peak_live_bytes, peak_index, peak_instruction,
    live_at_peak, resident_bytes, n_instructions}; `points` is
    [(instruction_index, live_bytes)] at every change, `live_at_peak`
    the slot rows occupying the peak, largest first.
    """
    if compiled is None:
        if program is None:
            raise ValueError("memory_timeline needs a program or a "
                             "compiled step")
        from ..core.executor import Executor

        exe = exe or Executor()
        compiled = exe.compiled_step(program, feed=feed,
                                     fetch_list=fetch_list, scope=scope)
    proto, _stats = compiled_memory_proto(compiled)
    ba = parse_buffer_assignment(proto)
    module = HloModule(proto)
    entry, pos, comp_pos, instr_comp = _module_positions(module)
    by_id = {i.id: i for comp in module.computations.values()
             for i in comp.instructions}
    n = len(entry.instructions)
    last_use: Dict[int, int] = {}
    for k, i in enumerate(entry.instructions):
        for oid in i.operand_ids:
            last_use[oid] = k

    def instr_pos(instr_id: Optional[int]) -> Optional[int]:
        if instr_id is None:
            return None
        if instr_id in pos:
            return pos[instr_id]
        cid = instr_comp.get(instr_id)
        return comp_pos.get(cid) if cid is not None else None

    slots: List[Dict[str, Any]] = []
    resident = 0
    if ba is not None:
        for a in ba.allocations:
            if a.is_param or a.is_constant or a.live_out:
                resident += a.size
                continue
            # group assigned buffers into offset-overlap slots
            spans = []
            for bid, off, sz in sorted(a.assigned, key=lambda t: t[1]):
                lb = ba.buffers.get(bid)
                if lb is None or sz <= 0:
                    continue
                p = instr_pos(lb.instr_id)
                if p is None:
                    p = 0
                lo = p
                hi = max(last_use.get(lb.instr_id, p), p) \
                    if lb.instr_id in pos else n - 1
                instr = by_id.get(lb.instr_id)
                if spans and off < spans[-1]["end"]:
                    s = spans[-1]
                    s["end"] = max(s["end"], off + sz)
                    s["lo"] = min(s["lo"], lo)
                    s["hi"] = max(s["hi"], hi)
                    s["buffers"].append(lb.id)
                else:
                    spans.append({"start": off, "end": off + sz,
                                  "lo": lo, "hi": hi,
                                  "buffers": [lb.id],
                                  "op_type": (fluid_op_of(instr.op_name)
                                              if instr is not None
                                              else None),
                                  "instruction": (instr.name
                                                  if instr is not None
                                                  else None)})
            for s in spans:
                slots.append({"bytes": s["end"] - s["start"],
                              "lo": s["lo"], "hi": s["hi"],
                              "op_type": s["op_type"],
                              "instruction": s["instruction"],
                              "buffers": s["buffers"]})
    else:
        # fallback: the module-shapes sweep's buffers are the slots
        for k, i in enumerate(entry.instructions):
            nbytes = i.shape.bytes
            if nbytes <= 0 or i.opcode in (
                    "parameter", "constant", "tuple",
                    "get-tuple-element", "bitcast"):
                if i.opcode == "parameter" or i.id == entry.root_id:
                    resident += max(nbytes, 0)
                continue
            if i.id == entry.root_id:
                resident += nbytes
                continue
            slots.append({"bytes": nbytes, "lo": k,
                          "hi": max(last_use.get(i.id, k), k),
                          "op_type": fluid_op_of(i.op_name),
                          "instruction": i.name, "buffers": [i.id]})

    deltas = [0] * (n + 1)
    for s in slots:
        deltas[s["lo"]] += s["bytes"]
        deltas[min(s["hi"], n - 1) + 1] -= s["bytes"]
    points: List[Tuple[int, int]] = []
    live, peak, peak_idx = resident, resident, 0
    for k in range(n):
        if deltas[k]:
            live += deltas[k]
            points.append((k, live))
            if live > peak:
                peak, peak_idx = live, k
    if not points:
        points = [(0, resident)]
    live_at_peak = sorted(
        (s for s in slots if s["lo"] <= peak_idx <= s["hi"]),
        key=lambda s: -s["bytes"])
    peak_instr = entry.instructions[peak_idx].name \
        if peak_idx < n else None
    return {
        "points": points,
        "peak_live_bytes": int(peak),
        "peak_index": peak_idx,
        "peak_instruction": peak_instr,
        "live_at_peak": live_at_peak,
        "resident_bytes": int(resident),
        "n_instructions": n,
        "source": "buffer_assignment" if ba is not None
                  else "module-shapes",
    }


def export_chrome_trace(timeline: Dict[str, Any], path: str) -> str:
    """Write the timeline as chrome-trace JSON (counter events over the
    instruction schedule + an instant event at the peak) — load in
    chrome://tracing or Perfetto next to a jax.profiler trace."""
    import json

    events = [{"name": "live_hbm_bytes", "ph": "C", "pid": 0, "tid": 0,
               "ts": idx, "args": {"bytes": live}}
              for idx, live in timeline["points"]]
    events.append({
        "name": "peak", "ph": "i", "pid": 0, "tid": 0, "s": "g",
        "ts": timeline["peak_index"],
        "args": {"peak_live_bytes": timeline["peak_live_bytes"],
                 "instruction": timeline["peak_instruction"],
                 "top_buffers": [
                     {"bytes": s["bytes"], "op_type": s["op_type"],
                      "instruction": s["instruction"]}
                     for s in timeline["live_at_peak"][:10]]},
    })
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return path


# --------------------------------------------------------------------------
# the fit planner
# --------------------------------------------------------------------------

def _feed_spec(feed) -> Dict[str, Any]:
    import jax
    import numpy as np

    out = {}
    for n, v in (feed or {}).items():
        if isinstance(v, jax.ShapeDtypeStruct):
            out[n] = v
        else:
            arr = np.asarray(v)
            out[n] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
    return out


def _infer_batch(spec: Dict[str, Any]) -> Optional[int]:
    from collections import Counter

    dims = Counter(int(s.shape[0]) for s in spec.values() if s.shape)
    if not dims:
        return None
    return dims.most_common(1)[0][0]


def plan_fit(program, feed, fetch_list=None, scope=None, exe=None,
             batch: Optional[int] = None,
             probe_batches: Tuple[int, int] = (2, 4),
             budget_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Predict the step's peak device memory for a CANDIDATE feed
    without compiling the candidate.

    `feed` maps input name → array or jax.ShapeDtypeStruct at the
    candidate shape (no data needed).  The planner compiles the SAME
    program at two small probe batches — every feed whose leading dim
    equals the candidate batch is shrunk, everything else (seq length,
    dtype, the program's remat structure) stays at the candidate value
    — and extrapolates the affine peak(b) fit.  Probe compiles are
    memoized in the executor's AOT cache, so planning a whole ladder of
    batches pays the two compiles once.

    Returns {predicted_peak_bytes, batch, probe_batches, probe_peaks,
    per_example_bytes, resident_bytes, breakdown, rel_tol, budget_bytes,
    fits, headroom_bytes}; `fits`/`headroom_bytes` are None when no
    budget is known (budget_bytes argument, else the live device
    budget).  `rel_tol` is the recorded accuracy bound
    (PLAN_FIT_REL_TOL) of the prediction vs a real same-backend
    measurement.  Raises ValueError when the batch axis cannot be
    inferred (pass batch=).
    """
    import jax

    from ..core.executor import Executor

    exe = exe or Executor()
    spec = _feed_spec(feed)
    if not spec:
        raise ValueError("plan_fit needs a feed (the candidate shapes; "
                         "programs with no feeds have nothing to scale)")
    batch = batch if batch is not None else _infer_batch(spec)
    if batch is None or batch < 1:
        raise ValueError(f"cannot infer the batch axis from {spec}; "
                         f"pass batch=")

    def at_batch(b: int) -> Dict[str, Any]:
        out = {}
        for n, s in spec.items():
            if s.shape and int(s.shape[0]) == batch:
                out[n] = jax.ShapeDtypeStruct((b,) + tuple(s.shape[1:]),
                                              s.dtype)
            else:
                out[n] = s
        return out

    def peak_at(b: int) -> Tuple[int, Any]:
        compiled = exe.compiled_step(program, feed=at_batch(b),
                                     fetch_list=fetch_list, scope=scope)
        peak = compiled_peak_bytes(compiled)
        if peak is None:
            raise RuntimeError(
                "backend exposes no memory analysis — plan_fit cannot "
                "probe on this platform")
        return peak, compiled

    b0, b1 = sorted(int(b) for b in probe_batches)
    if not (0 < b0 < b1):
        raise ValueError(f"probe_batches must be two distinct positive "
                         f"sizes, got {probe_batches}")
    if batch <= b1:
        # candidate is probe-sized: measure it directly (exact)
        peak, _ = peak_at(batch)
        p0 = p1 = peak
        slope, intercept = 0.0, float(peak)
        predicted = peak
        exact = True
    else:
        p0, _ = peak_at(b0)
        p1, _ = peak_at(b1)
        slope = (p1 - p0) / float(b1 - b0)
        intercept = p0 - slope * b0
        predicted = int(round(intercept + slope * batch))
        exact = False

    # exact resident components from the program/state (chip-free)
    params, opt = _program_var_buckets(program)
    from ..core.executor import global_scope

    sc = scope if scope is not None else global_scope()
    import numpy as np

    def _nbytes(name):
        v = sc.find_var(name)
        if v is None:
            return 0
        try:
            return int(np.asarray(v).nbytes)
        except Exception:  # noqa: BLE001
            return 0

    params_bytes = sum(_nbytes(n) for n in params)
    opt_bytes = sum(_nbytes(n) for n in opt)
    feed_bytes = int(sum(
        int(np.prod(s.shape, dtype=np.int64) or 1)
        * np.dtype(s.dtype).itemsize for s in spec.values()))

    if budget_bytes is None:
        budget_bytes = device_memory_budget()
    fits = headroom = None
    if budget_bytes:
        fits = bool(predicted <= budget_bytes)
        headroom = int(budget_bytes - predicted)
    return {
        "predicted_peak_bytes": int(predicted),
        "exact": exact,
        "batch": int(batch),
        "probe_batches": [b0, b1] if not exact else [batch],
        "probe_peaks": [int(p0), int(p1)] if not exact else [int(p0)],
        "per_example_bytes": int(round(slope)),
        "resident_bytes": int(round(intercept)),
        "breakdown": {
            "params": params_bytes,
            "optimizer_state": opt_bytes,
            "feeds": feed_bytes,
            "temp": int(max(predicted - params_bytes - opt_bytes
                            - feed_bytes, 0)),
        },
        "rel_tol": PLAN_FIT_REL_TOL,
        "budget_bytes": budget_bytes,
        "fits": fits,
        "headroom_bytes": headroom,
    }
