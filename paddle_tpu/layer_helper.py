"""LayerHelper: shared plumbing for layer functions.

reference: python/paddle/fluid/layer_helper.py — parameter creation with
initializers/regularizers, dtype inference, activation append.
"""

from __future__ import annotations

from typing import Optional

from .core import unique_name
from .core.program import (Parameter, Program, Variable,
                           default_main_program, default_startup_program)
from .initializer import Constant, Xavier
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name.generate(layer_type)

    @property
    def main_program(self) -> Program:
        return default_main_program()

    @property
    def startup_program(self) -> Program:
        return default_startup_program()

    # -- inputs ----------------------------------------------------------
    def input(self, input_param_name: str = "input"):
        inputs = self.kwargs.get(input_param_name)
        if isinstance(inputs, (list, tuple)):
            return list(inputs)
        return inputs

    def input_dtype(self, input_param_name: str = "input") -> str:
        inputs = self.input(input_param_name)
        if isinstance(inputs, list):
            return inputs[0].dtype
        return inputs.dtype

    # -- var/param creation ----------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias: bool = False,
                         default_initializer=None) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")
        if default_initializer is None:
            default_initializer = Constant(0.0) if is_bias else Xavier()
        init = attr.initializer or default_initializer

        main_block = self.main_program.global_block()
        if main_block.has_var(name):
            if not attr.name:
                raise ValueError(f"parameter {name!r} already exists")
            # fluid parameter sharing: an EXPLICITLY named ParamAttr
            # reuses the existing parameter (the reference book models
            # share embeddings this way — test_label_semantic_roles.py
            # binds 6 features to one 'emb' table); generated names
            # colliding is still a bug and still raises
            existing = main_block.var(name)
            if not isinstance(existing, Parameter):
                raise ValueError(
                    f"name {name!r} already belongs to a non-parameter "
                    f"variable; cannot share it as a layer weight")
            if (tuple(existing.shape) != tuple(shape)
                    or str(existing.dtype) != str(dtype)):
                raise ValueError(
                    f"shared parameter {name!r} re-declared with "
                    f"mismatched shape/dtype: existing "
                    f"{existing.shape}/{existing.dtype} vs requested "
                    f"{tuple(shape)}/{dtype}")
            # a second declaration cannot re-configure the parameter —
            # silently dropping its attrs would make hyperparameter
            # edits on the later site no-ops
            if attr.learning_rate != getattr(existing, "learning_rate",
                                             attr.learning_rate):
                raise ValueError(
                    f"shared parameter {name!r} re-declared with a "
                    f"different learning_rate "
                    f"({existing.learning_rate} vs "
                    f"{attr.learning_rate}); attrs bind at the FIRST "
                    f"declaration")
            if attr.initializer is not None or attr.regularizer is not None:
                raise ValueError(
                    f"shared parameter {name!r}: initializer/"
                    f"regularizer on a re-declaration cannot apply — "
                    f"set them where the parameter is first declared")
            return existing
        param = main_block.create_parameter(
            name, shape, dtype,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
            learning_rate=attr.learning_rate,
            trainable=attr.trainable,
        )
        # Mirror into the startup program with its init op (fluid
        # layer_helper.py creates the startup var + initializer op).
        startup_block = self.startup_program.global_block()
        sp_var = startup_block.create_parameter(name, shape, dtype)
        init(sp_var, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype) -> Variable:
        # Temporaries live in the *current* block so layers called inside
        # control-flow sub-blocks (While/StaticRNN bodies) stay local to
        # them; parameters always live in the global block, as in fluid.
        return self.main_program.current_block().create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            dtype=dtype,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, shape, dtype, name=None,
                               persistable=False) -> Variable:
        return self.main_program.global_block().create_var(
            name=name or unique_name.generate(f"{self.name}.global"),
            shape=shape, dtype=dtype, persistable=persistable,
        )

    def create_or_get_global_variable(self, name, shape, dtype,
                                      persistable=True,
                                      initializer=None) -> Variable:
        """Persistable non-parameter state var (metric buffers, counters),
        mirrored into the startup program with its initializer."""
        block = self.main_program.global_block()
        if block.has_var(name):
            return block.var(name)
        var = block.create_var(name=name, shape=shape, dtype=dtype,
                               persistable=persistable, stop_gradient=True)
        startup_block = self.startup_program.global_block()
        if not startup_block.has_var(name):
            sp = startup_block.create_var(
                name=name, shape=shape, dtype=dtype, persistable=True,
                stop_gradient=True)
            (initializer or Constant(0.0))(sp, startup_block)
        return var

    # -- op appending -----------------------------------------------------
    def append_op(self, **kwargs):
        return self.main_program.current_block().append_op(**kwargs)

    def append_activation(self, input_var: Variable) -> Variable:
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [out]}, attrs=act)
        return out

    def append_bias_op(self, input_var: Variable, dim_start: int = 1,
                       bias_attr=None) -> Variable:
        attr = ParamAttr._to_attr(
            bias_attr if bias_attr is not None
            else self.kwargs.get("bias_attr"))
        if attr is None:
            return input_var
        size = input_var.shape[dim_start:]
        b = self.create_parameter(attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [b]},
                       outputs={"Out": [out]}, attrs={"axis": dim_start})
        return out
