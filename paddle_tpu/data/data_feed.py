"""DataFeed: file-shard parsing for the async CTR training path.

TPU-native analog of the reference's DataFeed stack
(reference: paddle/fluid/framework/data_feed.h:49 — DataFeed virtual
reader; MultiSlotDataFeed text parser; data_feed.proto schema;
python/paddle/fluid/data_feed_desc.py DataFeedDesc wrapper).

The MultiSlot text format (one sample per line): for each slot in schema
order, an integer count N followed by N values (ints for sparse id
slots, floats for dense slots), whitespace-separated — the classic CTR
log line.  Batches come out as padded numpy dicts matching the
framework's padded+seq_len ragged representation.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class DataFeedDesc:
    """Schema for MultiSlot parsing (reference data_feed_desc.py, backed
    by data_feed.proto; JSON here instead of protobuf text).

        desc = DataFeedDesc.from_slots([
            {"name": "ids", "type": "uint64", "dense": False,
             "max_len": 20},
            {"name": "dense_vals", "type": "float", "dense": True,
             "dim": 13},
            {"name": "label", "type": "uint64", "dense": True, "dim": 1},
        ], batch_size=32)
    """

    def __init__(self, proto_desc: Optional[str] = None):
        self.slots: List[dict] = []
        self.batch_size = 1
        if proto_desc:
            d = json.loads(proto_desc)
            self.slots = d["slots"]
            self.batch_size = d.get("batch_size", 1)

    @classmethod
    def from_slots(cls, slots: Sequence[dict], batch_size: int = 1):
        desc = cls()
        desc.slots = [dict(s) for s in slots]
        desc.batch_size = batch_size
        return desc

    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_use_slots(self, use_slots: Sequence[str]):
        use = set(use_slots)
        for s in self.slots:
            s["used"] = s["name"] in use

    def desc(self) -> str:
        return json.dumps({"slots": self.slots,
                           "batch_size": self.batch_size})


class MultiSlotDataFeed:
    """Parser over text file shards (reference MultiSlotDataFeed,
    data_feed.cc).  Yields padded batch dicts: sparse slots become
    (B, max_len) int64 + "<name>.seq_len"; dense slots (B, dim)."""

    def __init__(self, desc: DataFeedDesc):
        self.desc = desc

    def _parse_line(self, line: str):
        toks = line.split()
        pos = 0
        sample = {}
        for slot in self.desc.slots:
            n = int(toks[pos])
            pos += 1
            vals = toks[pos:pos + n]
            if len(vals) != n:
                raise ValueError(
                    f"corrupt MultiSlot line: slot {slot['name']!r} "
                    f"declares {n} values, found {len(vals)}")
            pos += n
            if slot.get("used", True) is False:
                continue
            if slot.get("type", "uint64").startswith("float"):
                sample[slot["name"]] = np.asarray(vals, np.float32)
            else:
                # CTR hash ids use the full uint64 range; parse as uint64
                # then reinterpret into the framework's int64 id dtype
                # (bit pattern preserved, distinctness preserved)
                sample[slot["name"]] = np.asarray(
                    [int(v) for v in vals], np.uint64).astype(np.int64)
        return sample

    def read_file(self, path: str) -> Iterable[dict]:
        # RecordIO shards (sniffed by chunk magic) carry one MultiSlot
        # line per record — the reference's recordio DataFeed variant
        # (data_feed.cc MultiSlotType over recordio chunks); plain files
        # are newline-separated text
        if self._is_recordio(path):
            from . import recordio

            for rec in recordio.Scanner(path):
                line = rec.decode("utf-8").strip()
                if line:
                    yield self._parse_line(line)
            return
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield self._parse_line(line)

    @staticmethod
    def _is_recordio(path: str) -> bool:
        from . import recordio

        with open(path, "rb") as f:
            head = f.read(4)
        return (len(head) == 4 and
                int.from_bytes(head, "little") == recordio.MAGIC)

    def batches(self, paths: Sequence[str]) -> Iterable[Dict[str, np.ndarray]]:
        buf: List[dict] = []
        bs = self.desc.batch_size
        for p in paths:
            for sample in self.read_file(p):
                buf.append(sample)
                if len(buf) == bs:
                    yield self._collate(buf)
                    buf = []
        # trailing partial batch dropped (static shapes; reference's
        # DataFeed also pads/drops at shard ends)

    def _collate(self, samples: List[dict]) -> Dict[str, np.ndarray]:
        batch: Dict[str, np.ndarray] = {}
        for slot in self.desc.slots:
            if slot.get("used", True) is False:
                continue
            name = slot["name"]
            vals = [s[name] for s in samples]
            if slot.get("dense", False):
                dim = int(slot.get("dim", len(vals[0])))
                arr = np.zeros((len(vals), dim), vals[0].dtype)
                for i, v in enumerate(vals):
                    arr[i, :len(v)] = v[:dim]
                batch[name] = arr
            else:
                if "max_len" not in slot:
                    raise ValueError(
                        f"sparse slot {name!r} needs a 'max_len': batch "
                        f"shapes must be static (padding to each batch's "
                        f"own max would retrigger XLA compilation per "
                        f"batch and break declared feed shapes)")
                max_len = int(slot["max_len"])
                arr = np.zeros((len(vals), max_len), np.int64)
                lens = np.zeros((len(vals),), np.int32)
                for i, v in enumerate(vals):
                    k = min(len(v), max_len)
                    arr[i, :k] = v[:k]
                    lens[i] = k
                batch[name] = arr
                batch[f"{name}.seq_len"] = lens
        return batch
