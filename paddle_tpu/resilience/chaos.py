"""Deterministic fault injection for the resilience subsystem.

Every recovery behavior in this repo is proven by injecting its fault
(tests/test_resilience.py, the run_ci.sh chaos smoke), not by hoping:

- **failpoints** — named kill-switches compiled into the production
  code path at the exact spots a process can die (e.g.
  `ckpt:before_manifest` between the shard write and the manifest
  write in io.save_sharded).  Unarmed they are a dict lookup; armed
  they raise `ChaosKilled`, simulating preemption at that instant.
- **NaN injection** — poison one named feed at step k of a reader
  (host-side; the NaN propagates to loss and every gradient, which is
  exactly the production failure mode a bad batch causes).
- **checkpoint corruption** — flip or truncate bytes of a shard
  container so CRC/container verification must catch it.
- **executor faults** — `FlakyPredictor` wraps a real Predictor and
  fails (or delays) the first N `run()` calls: the serving circuit
  breaker's failure-burst-then-recover story.
- **hang** — a sleep the watchdog must interrupt.

Injectors are deterministic (step counts, call counts — never random),
so every chaos test is reproducible.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

from .errors import ResilienceError


class ChaosKilled(ResilienceError):
    """Raised by an armed failpoint — the simulated process death."""

    kind = "chaos_killed"


# ---------------------------------------------------------------------------
# Failpoints
# ---------------------------------------------------------------------------

_armed: Dict[str, int] = {}
_delays: Dict[str, tuple] = {}  # name -> (seconds, remaining hits)


def arm(name: str, times: int = 1) -> None:
    """Arm failpoint `name` to fire on its next `times` hits."""
    _armed[name] = int(times)


def arm_delay(name: str, seconds: float, times: int = 1) -> None:
    """Arm delaypoint `name` to SLEEP `seconds` on its next `times`
    hits — the slow-disk/slow-fsync injection the async-checkpoint
    tests use to prove the step loop is not blocked by the write
    phase (a failpoint kills; a delaypoint stalls)."""
    _delays[name] = (float(seconds), int(times))


def disarm(name: str) -> None:
    _armed.pop(name, None)
    _delays.pop(name, None)


def clear() -> None:
    """Disarm every failpoint and delaypoint (test teardown)."""
    _armed.clear()
    _delays.clear()


def failpoint(name: str) -> None:
    """Production-code hook: no-op unless `arm(name)` was called, then
    raises ChaosKilled (once per armed count)."""
    left = _armed.get(name)
    if not left:
        return
    if left <= 1:
        _armed.pop(name, None)
    else:
        _armed[name] = left - 1
    raise ChaosKilled(f"failpoint {name!r} fired (simulated death)",
                      failpoint=name)


def delaypoint(name: str) -> None:
    """Production-code hook: no-op unless `arm_delay(name, s)` was
    called, then sleeps the armed duration (once per armed count)."""
    entry = _delays.get(name)
    if not entry:
        return
    seconds, left = entry
    if left <= 1:
        _delays.pop(name, None)
    else:
        _delays[name] = (seconds, left - 1)
    time.sleep(seconds)


# ---------------------------------------------------------------------------
# NaN / feed poisoning
# ---------------------------------------------------------------------------

def poison_feed(feed: Dict[str, Any], names: Optional[Iterable[str]]
                = None) -> Dict[str, Any]:
    """Copy of `feed` with NaN written into the first element of each
    named float input (all float inputs when names is None)."""
    import numpy as np

    out = dict(feed)
    targets = list(names) if names is not None else [
        n for n, v in feed.items()
        if np.asarray(v).dtype.kind == "f"]
    if not targets:
        raise ValueError("no float feed to poison")
    for n in targets:
        arr = np.array(feed[n], copy=True)
        if arr.dtype.kind != "f":
            raise ValueError(f"feed {n!r} is {arr.dtype}, not float")
        arr.reshape(-1)[0] = np.nan
        out[n] = arr
    return out


def nan_reader(reader: Callable[[], Iterable], at_step: int,
               names: Optional[Iterable[str]] = None,
               feed_order: Optional[Iterable[str]] = None
               ) -> Callable[[], Iterator]:
    """Wrap a Trainer-style reader so the batch at index `at_step`
    (0-based, per epoch) is NaN-poisoned.  Tuple batches need
    `feed_order` to name their fields."""

    def wrapped():
        for i, batch in enumerate(reader()):
            if i != at_step:
                yield batch
                continue
            if not isinstance(batch, dict):
                if feed_order is None:
                    raise ValueError("tuple batches need feed_order")
                batch = dict(zip(feed_order, batch))
            yield poison_feed(batch, names)

    return wrapped


# ---------------------------------------------------------------------------
# Checkpoint corruption
# ---------------------------------------------------------------------------

def corrupt_file(path: str, mode: str = "flip",
                 offset_frac: float = 0.5) -> str:
    """Corrupt `path` in place: mode="flip" inverts 64 bytes in the
    middle (container still opens; content/CRC is wrong), mode=
    "truncate" cuts the file in half (container itself unreadable).
    Returns the path."""
    import os

    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return path
    if mode != "flip":
        raise ValueError(f"unknown corruption mode {mode!r}")
    off = min(max(0, int(size * offset_frac)), size - 1)
    n = min(64, size - off)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return path


def corrupt_shard(ckpt_dir: str, proc: int = 0,
                  mode: str = "flip") -> str:
    """Corrupt one shard container of a sharded checkpoint directory
    (io.py layout: shards_p{proc}.npz)."""
    import os

    path = os.path.join(ckpt_dir, f"shards_p{proc}.npz")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no shard file at {path}")
    return corrupt_file(path, mode=mode)


def tear_checkpoint(ckpt_dir: str) -> None:
    """Make an existing checkpoint directory look like a save that died
    between the shard write and the manifest write (shards present, no
    manifest, no trainer state) — the end-state the
    `ckpt:before_manifest` failpoint produces live."""
    import os

    from .. import io as fluid_io

    removed = 0
    for name in (fluid_io.SHARD_MANIFEST, "__trainer_state__.json"):
        p = os.path.join(ckpt_dir, name)
        if os.path.exists(p):
            os.remove(p)
            removed += 1
    if removed == 0:
        raise FileNotFoundError(
            f"{ckpt_dir} has no manifest/trainer state to tear")


# ---------------------------------------------------------------------------
# Executor faults (serving breaker, watchdog)
# ---------------------------------------------------------------------------

class InjectedExecutorError(ResilienceError):
    """The failure FlakyPredictor injects."""

    kind = "injected_executor_error"


class FlakyPredictor:
    """Predictor proxy whose `run()` fails for the first `fail_first`
    calls (optionally delaying `delay_s` before each call) and then
    behaves normally — a deterministic executor-failure burst.  All
    other attributes (compile_signature, get_input_names, ...) pass
    through, so warmup and shape validation are unaffected."""

    def __init__(self, predictor, fail_first: int = 0,
                 delay_s: float = 0.0):
        self._predictor = predictor
        self.fail_first = int(fail_first)
        self.delay_s = float(delay_s)
        self.calls = 0
        self.failures_injected = 0

    def run(self, feed):
        self.calls += 1
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.calls <= self.fail_first:
            self.failures_injected += 1
            raise InjectedExecutorError(
                f"injected executor failure {self.calls}/"
                f"{self.fail_first}", call=self.calls)
        return self._predictor.run(feed)

    def __getattr__(self, name):
        return getattr(self._predictor, name)


def _replica_point(engine, what: str) -> str:
    rid = getattr(engine, "replica_id", None)
    if rid is None:
        raise ValueError(
            "engine has no replica_id — chaos replica primitives "
            "target FLEET replicas (Fleet assigns ids at construction, "
            "or call engine.set_replica_id first)")
    return f"replica:{rid}:{what}"


def kill_replica(engine) -> str:
    """Arm the abrupt-death failpoint of one fleet replica: the
    engine's next scheduled iteration raises ChaosKilled exactly where
    an executor crash would land, driving the REAL failure path — a
    DecodeEngine's scheduler dies through `_fail_everything` (every
    in-flight request resolves with the structured retryable
    DecodeReplicaFailedError and a router fails it over); a
    ServingEngine's next dispatch fails the batch with the retryable
    ExecutorFailureError.  The in-process analog of SIGKILLing a
    replica process, with the same caller-visible evidence.  Returns
    the armed failpoint name (chaos.disarm(name) cancels)."""
    name = _replica_point(engine, "kill")
    arm(name)
    return name


def delay_replica(engine, seconds: float, times: int = 1) -> str:
    """Arm a per-iteration stall on one fleet replica — the straggler
    a router's hedging must beat.  Returns the delaypoint name."""
    name = _replica_point(engine, "delay")
    arm_delay(name, seconds, times)
    return name


def hang(seconds: float) -> None:
    """An injected hang the watchdog must interrupt (sleep re-enters
    the interpreter, so SIGALRM / the timer-thread async-exc can
    fire)."""
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# Gang chaos: env-armed per-rank failpoints for worker SUBPROCESSES
# (docs/RESILIENCE.md chaos registry).  The in-process arm()/failpoint()
# pair cannot reach a worker the supervisor spawned; these are armed
# through the environment the supervisor already propagates, and each
# carries an optional once-file so a relaunched gang (same env!) does
# not re-fire after the restart resumes past the arm step.
# ---------------------------------------------------------------------------

KILL_RANK_ENV = "PTPU_CHAOS_KILL_RANK"
KILL_STEP_ENV = "PTPU_CHAOS_KILL_STEP"
KILL_ONCE_ENV = "PTPU_CHAOS_KILL_ONCE_FILE"
HANG_RANK_ENV = "PTPU_CHAOS_HANG_RANK"
HANG_STEP_ENV = "PTPU_CHAOS_HANG_STEP"
HANG_S_ENV = "PTPU_CHAOS_HANG_S"
HANG_ONCE_ENV = "PTPU_CHAOS_HANG_ONCE_FILE"


def _env_armed(rank: int, step: int, rank_env: str, step_env: str,
               once_env: str) -> bool:
    import os

    target = os.environ.get(rank_env)
    if target is None or int(target) != int(rank):
        return False
    if int(step) < int(os.environ.get(step_env, "0")):
        return False
    once = os.environ.get(once_env)
    if once:
        if os.path.exists(once):
            return False  # already fired in a previous life
        with open(once, "w") as f:
            f.write(f"fired rank={rank} step={step}\n")
    return True


def kill_rank(rank: int, step: int) -> None:
    """SIGKILL-abrupt self-death when the environment arms this
    (rank, >=step): KILL_RANK_ENV / KILL_STEP_ENV, optional
    KILL_ONCE_ENV sentinel file for fire-exactly-once-across-restarts.
    Call from the worker's step loop — the real preemption the health
    plane must detect (no flush, no cleanup, like the preempt_worker
    SIGKILL timing but armed from env instead of a watching parent)."""
    import os
    import signal

    if _env_armed(rank, step, KILL_RANK_ENV, KILL_STEP_ENV,
                  KILL_ONCE_ENV):
        os.kill(os.getpid(), signal.SIGKILL)


def hang_rank(rank: int, step: int) -> None:
    """Hang this rank for HANG_S_ENV seconds (default 3600 — "forever"
    at test scale) when env-armed for (rank, >=step): the
    alive-but-stuck peer the stall detector / dispatch watchdog must
    catch.  Same once-file contract as kill_rank."""
    import os

    if _env_armed(rank, step, HANG_RANK_ENV, HANG_STEP_ENV,
                  HANG_ONCE_ENV):
        hang(float(os.environ.get(HANG_S_ENV, "3600")))


def arm_kill_rank_env(env: dict, rank: int, at_step: int,
                      once_file: Optional[str] = None) -> dict:
    """Fill `env` (in place, returned) with the kill_rank arming —
    the supervisor/test-side pairing of kill_rank()."""
    env[KILL_RANK_ENV] = str(rank)
    env[KILL_STEP_ENV] = str(at_step)
    if once_file:
        env[KILL_ONCE_ENV] = once_file
    return env


def arm_hang_rank_env(env: dict, rank: int, at_step: int,
                      seconds: float = 3600.0,
                      once_file: Optional[str] = None) -> dict:
    """env-side pairing of hang_rank()."""
    env[HANG_RANK_ENV] = str(rank)
    env[HANG_STEP_ENV] = str(at_step)
    env[HANG_S_ENV] = str(seconds)
    if once_file:
        env[HANG_ONCE_ENV] = once_file
    return env


# ---------------------------------------------------------------------------
# Fake KV store (health-plane unit tests)
# ---------------------------------------------------------------------------

class FakeKv:
    """In-process stand-in for the jax.distributed coordination KV
    client, with the exact method surface resilience/health.py and
    io._barrier use (key_value_set(+allow_overwrite) /
    key_value_dir_get / blocking_key_value_get / key_value_delete) —
    detection-window tests inject it with a fake clock instead of
    killing real processes.  Thread-safe; `fail_with` makes every call
    raise (the dead-coordinator simulation)."""

    def __init__(self):
        import threading

        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()
        self.fail_with: Optional[Exception] = None

    def _maybe_fail(self):
        if self.fail_with is not None:
            raise self.fail_with

    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = False) -> None:
        self._maybe_fail()
        with self._lock:
            if key in self._data and not allow_overwrite:
                raise RuntimeError(f"ALREADY_EXISTS: {key}")
            self._data[key] = value

    def key_value_dir_get(self, prefix: str):
        self._maybe_fail()
        prefix = prefix.rstrip("/") + "/"
        with self._lock:
            return sorted((k, v) for k, v in self._data.items()
                          if k.startswith(prefix))

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        self._maybe_fail()
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            with self._lock:
                if key in self._data:
                    return self._data[key]
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"GetKeyValue timed out with key: {key}")
            time.sleep(0.005)

    def key_value_delete(self, key: str) -> None:
        self._maybe_fail()
        with self._lock:
            if key.endswith("/"):
                for k in [k for k in self._data if k.startswith(key)]:
                    del self._data[k]
            else:
                self._data.pop(key, None)
