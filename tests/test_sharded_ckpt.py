"""Sharded checkpoint save/load (VERDICT round-2 item 5).

Contract: save writes per-process shard files keyed by each shard's
global index (no one-host gather of the full state); load reassembles
directly into the target NamedShardings; training resumed from a
sharded checkpoint matches uninterrupted training exactly.

reference analog: per-pserver parameter slices,
transpiler/distribute_transpiler.py:894 (_get_slice_vars_and_attrs).
"""

import json
import os

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import ShardingRules, make_mesh


def _build(seed=11):
    x = layers.data(name="x", shape=[16], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu", name="ffn_in")
    logits = layers.fc(h, size=8, name="ffn_out")
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.MomentumOptimizer(learning_rate=0.1,
                                      momentum=0.9).minimize(loss)
    return loss


def _rules():
    # Megatron pairing over mp: column-parallel in, row-parallel out
    return ShardingRules(rules=[
        (r"ffn_in\S*\.w", (None, "mp")),
        (r"ffn_out\S*\.w", ("mp", None)),
    ])


def _compiled(main, loss, mesh):
    bs = fluid.BuildStrategy()
    bs.sharding_rules = _rules()
    return fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs, mesh=mesh)


def _batches(n, seed=5):
    rng = np.random.RandomState(seed)
    return [(rng.randn(32, 16).astype(np.float32),
             rng.randint(0, 8, (32, 1)).astype(np.int64))
            for _ in range(n)]


def test_sharded_resume_parity(tmp_path):
    """Train 2 steps → save_sharded → fresh program/scope on a fresh
    mesh → load_sharded → 2 more steps == 4 uninterrupted steps."""
    mesh = make_mesh({"dp": 2, "mp": 4})
    batches = _batches(4)

    # uninterrupted run
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    scope = fluid.Scope()
    ref_losses = []
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = _build()
        exe = fluid.Executor()
        exe.run(startup)
        prog = _compiled(main, loss, mesh)
        for xv, yv in batches:
            (lv,) = exe.run(prog, feed={"x": xv, "y": yv},
                            fetch_list=[loss])
            ref_losses.append(float(np.asarray(lv).reshape(-1)[0]))

    ckpt = str(tmp_path / "ckpt")
    # interrupted run part 1
    main1, startup1 = fluid.Program(), fluid.Program()
    main1.random_seed = 3
    scope1 = fluid.Scope()
    with fluid.program_guard(main1, startup1), fluid.scope_guard(scope1), \
            fluid.unique_name.guard():
        loss1 = _build()
        exe = fluid.Executor()
        exe.run(startup1)
        prog1 = _compiled(main1, loss1, mesh)
        for xv, yv in batches[:2]:
            exe.run(prog1, feed={"x": xv, "y": yv}, fetch_list=[loss1])
        fluid.io.save_sharded(exe, ckpt, main_program=main1)

    # the manifest records true per-shard indices for the mp-sharded fc
    with open(os.path.join(ckpt, "__shards__.json")) as f:
        manifest = json.load(f)
    w_in = next(n for n in manifest["vars"] if "ffn_in" in n
                and ".w" in n)
    assert len(manifest["vars"][w_in]["shards"]) == 4  # mp=4 slices
    # and no shard holds the full (16, 32) array
    for sh in manifest["vars"][w_in]["shards"]:
        (a0, b0), (a1, b1) = sh["index"]
        assert (b0 - a0) * (b1 - a1) < 16 * 32

    # interrupted run part 2: fresh everything, load INTO shardings
    mesh2 = make_mesh({"dp": 2, "mp": 4})
    main2, startup2 = fluid.Program(), fluid.Program()
    main2.random_seed = 3
    scope2 = fluid.Scope()
    res_losses = []
    with fluid.program_guard(main2, startup2), fluid.scope_guard(scope2), \
            fluid.unique_name.guard():
        loss2 = _build()
        exe = fluid.Executor()
        exe.run(startup2)  # init then overwrite: exercises set_var path
        prog2 = _compiled(main2, loss2, mesh2)
        fluid.io.load_sharded(exe, ckpt, main_program=main2, mesh=mesh2,
                              sharding_rules=_rules())
        # loaded arrays are actually sharded, not replicated
        val = fluid.global_scope().find_var(w_in)
        assert val.sharding.num_devices_sharded > 1 if hasattr(
            val.sharding, "num_devices_sharded") else True
        shard_shapes = {s.data.shape for s in val.addressable_shards}
        assert (16, 8) in shard_shapes  # (16, 32) split 4-way on dim 1
        for xv, yv in batches[2:]:
            (lv,) = exe.run(prog2, feed={"x": xv, "y": yv},
                            fetch_list=[loss2])
            res_losses.append(float(np.asarray(lv).reshape(-1)[0]))

    np.testing.assert_allclose(res_losses, ref_losses[2:], rtol=1e-5,
                               atol=1e-6)


def test_sharded_roundtrip_host_fallback(tmp_path):
    """Without a mesh, load_sharded assembles host-side and matches the
    saved values bit-exactly."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    ckpt = str(tmp_path / "ck")
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        _build()
        exe = fluid.Executor()
        exe.run(startup)
        before = {
            v.name: np.asarray(fluid.global_scope().find_var(v.name))
            for v in main.list_vars() if v.persistable
        }
        fluid.io.save_sharded(exe, ckpt, main_program=main)
        # clobber, then reload
        for name, arr in before.items():
            fluid.global_scope().set_var(name, np.zeros_like(arr))
        fluid.io.load_sharded(exe, ckpt, main_program=main)
        for name, arr in before.items():
            got = np.asarray(fluid.global_scope().find_var(name))
            np.testing.assert_array_equal(got, arr, err_msg=name)


def test_bf16_state_roundtrip(tmp_path):
    """ADVICE r3 (medium): np.savez stores ml_dtypes arrays as void
    ('|V2'); save must stay loadable for bf16 persistables — both the
    sharded and the plain paths reinterpret via the manifest dtype."""
    import jax.numpy as jnp

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        _build()
        exe = fluid.Executor()
        exe.run(startup)
        gs = fluid.global_scope()
        names = [v.name for v in main.list_vars() if v.persistable]
        target = names[0]
        bf = jnp.asarray(np.asarray(gs.find_var(target)), jnp.bfloat16)
        gs.set_var(target, bf)
        want = np.asarray(bf)

        ck1 = str(tmp_path / "sharded")
        fluid.io.save_sharded(exe, ck1, main_program=main)
        gs.set_var(target, jnp.zeros_like(bf))
        fluid.io.load_sharded(exe, ck1, main_program=main)
        got = np.asarray(gs.find_var(target))
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(got, want)

        ck2 = str(tmp_path / "plain")
        gs.set_var(target, bf)
        fluid.io.save_persistables(exe, ck2, main_program=main)
        gs.set_var(target, jnp.zeros_like(bf))
        fluid.io.load_persistables(exe, ck2, main_program=main)
        got = np.asarray(gs.find_var(target))
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(got, want)


def test_load_sharded_missing_var_raises(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    ckpt = str(tmp_path / "ck")
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        _build()
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_sharded(exe, ckpt, main_program=main)
        os.remove(os.path.join(ckpt, "__shards__.json"))
        # a manifest-less directory is by design not a checkpoint; the
        # resilience subsystem turned the raw FileNotFoundError into a
        # structured CheckpointError so Trainer fallback can dispatch
        with pytest.raises(fluid.resilience.CheckpointNotFoundError):
            fluid.io.load_sharded(exe, ckpt, main_program=main)
