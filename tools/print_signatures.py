#!/usr/bin/env python3
"""Dump the public API surface as stable one-line signatures.

TPU-native analog of the reference's API-stability gate
(reference: tools/print_signatures.py + tools/diff_api.py — CI fails
when the dumped signature list drifts from the checked-in baseline).

Usage:
    python tools/print_signatures.py > tools/api_signatures.txt  # refresh
    python tools/diff_api.py                                     # gate
"""

from __future__ import annotations

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.layers.detection",
    "paddle_tpu.optimizer",
    "paddle_tpu.io",
    "paddle_tpu.inference",
    "paddle_tpu.quantize",
    "paddle_tpu.metrics",
    "paddle_tpu.parallel",
    "paddle_tpu.data.pipeline",
    "paddle_tpu.data.recordio",
    "paddle_tpu.data.data_feed",
    "paddle_tpu.contrib",
    "paddle_tpu.imperative",
    "paddle_tpu.observe",
    "paddle_tpu.resilience",
    "paddle_tpu.serving",
    "paddle_tpu.profiler",
]


def _signature_of(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def dump(out=sys.stdout):
    import importlib

    lines = []
    for mod_name in MODULES:
        mod = importlib.import_module(mod_name)
        for name in sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            qual = f"{mod_name}.{name}"
            if inspect.isfunction(obj):
                # only functions defined under the package (skip
                # re-exports of stdlib/jax helpers)
                if not (obj.__module__ or "").startswith("paddle_tpu"):
                    continue
                lines.append(f"{qual}{_signature_of(obj)}")
            elif inspect.isclass(obj):
                if not (obj.__module__ or "").startswith("paddle_tpu"):
                    continue
                lines.append(f"{qual}{_signature_of(obj.__init__)}")
    for line in sorted(set(lines)):
        print(line, file=out)


if __name__ == "__main__":
    dump()
