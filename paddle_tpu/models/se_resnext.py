"""SE-ResNeXt-50 (reference: benchmark/fluid/models/se_resnext.py)."""

from __future__ import annotations

from .. import layers, optimizer


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None):
    conv = layers.conv2d(input=input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = layers.pool2d(input=input, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio,
                        act="relu")
    excitation = layers.fc(input=squeeze, size=num_channels, act="sigmoid")
    return layers.elementwise_mul(x=input, y=excitation, axis=0)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        filter_size = 1
        return conv_bn_layer(input, ch_out, filter_size, stride)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio):
    conv0 = conv_bn_layer(input=input, num_filters=num_filters,
                          filter_size=1, act="relu")
    conv1 = conv_bn_layer(input=conv0, num_filters=num_filters,
                          filter_size=3, stride=stride, groups=cardinality,
                          act="relu")
    conv2 = conv_bn_layer(input=conv1, num_filters=num_filters * 2,
                          filter_size=1, act=None)
    scale = squeeze_excitation(conv2, num_channels=num_filters * 2,
                               reduction_ratio=reduction_ratio)
    short = shortcut(input, num_filters * 2, stride)
    return layers.elementwise_add(x=short, y=scale, act="relu")


def se_resnext(input, class_dim=1000, infer=False, layers_cfg=50):
    supported = {
        50: ([3, 4, 6, 3], [128, 256, 512, 1024]),
        152: ([3, 8, 36, 3], [128, 256, 512, 1024]),
    }
    depth, num_filters = supported[layers_cfg]
    cardinality = 32
    reduction_ratio = 16

    conv = conv_bn_layer(input=input, num_filters=64, filter_size=7,
                         stride=2, act="relu")
    conv = layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type="max")
    for block in range(len(depth)):
        for i in range(depth[block]):
            conv = bottleneck_block(
                input=conv, num_filters=num_filters[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality, reduction_ratio=reduction_ratio)
    pool = layers.pool2d(input=conv, pool_type="avg", global_pooling=True)
    if not infer:
        pool = layers.dropout(x=pool, dropout_prob=0.5)
    return layers.fc(input=pool, size=class_dim, act="softmax")


def build_model(class_dim=1000, learning_rate=0.1, with_optimizer=True,
                lr_boundaries=None, lr_values=None):
    input = layers.data(name="data", shape=[3, 224, 224], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    out = se_resnext(input, class_dim)
    cost = layers.cross_entropy(input=out, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=out, label=label)
    if with_optimizer:
        if lr_boundaries:
            lr = layers.piecewise_decay(boundaries=lr_boundaries,
                                        values=lr_values)
        else:
            lr = learning_rate
        opt = optimizer.MomentumOptimizer(learning_rate=lr, momentum=0.9)
        opt.minimize(avg_cost)
    return {"loss": avg_cost, "accuracy": acc, "feeds": ["data", "label"]}
