"""Model-compression framework: pruning passes + distillation helpers.

reference: python/paddle/fluid/contrib/slim/ — core/compress_pass.py:1
(CompressPass/Context driving strategies through epoch/batch events),
core/strategy.py (Strategy event hooks), prune/pruner.py:1
(MagnitudePruner/RatioPruner producing zero-masks),
prune/prune_strategy.py:38 (PruneStrategy re-applying masks every K
batches so pruned weights stay dead through fine-tuning).

TPU-native redesign: the reference built throwaway mask programs and
ran them through a graph executor per trigger; here parameters live as
device arrays in the Scope, so a pruning pass computes masks with jnp
and writes `param * mask` back between steps — no extra program build,
no host round-trip of the full weights (mask math stays on device).
Sparsity is *simulated* via zero weights (the reference did the same):
XLA has no sparse-tensor execution, so the win is model-size /
distillation-target quality, not FLOPs.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.executor import global_scope
from ..core.program import Parameter, default_main_program

__all__ = ["Pruner", "MagnitudePruner", "RatioPruner", "SlimContext",
           "PruneStrategy", "CompressPass", "Strategy",
           "sparsity", "distillation_loss"]


# ---------------------------------------------------------------------------
# Pruners: parameter -> 0/1 keep-mask
# ---------------------------------------------------------------------------

class Pruner:
    """Base pruner (reference prune/pruner.py Pruner)."""

    def mask(self, value, name: str = ""):
        """value: device array (+ the parameter's name, for pruners
        with per-name policies) -> 0/1 keep-mask of the same shape."""
        raise NotImplementedError


class MagnitudePruner(Pruner):
    """Keep weights with |w| >= threshold (reference MagnitudePruner —
    whose less_than mask keeps small weights zeroed)."""

    def __init__(self, threshold: float):
        self.threshold = float(threshold)

    def mask(self, value, name: str = ""):
        import jax.numpy as jnp

        return (jnp.abs(value) >= self.threshold).astype(value.dtype)


class RatioPruner(Pruner):
    """Prune the smallest-|w| `ratio` fraction per parameter (reference
    RatioPruner's per-param ratios; a float applies to every param, a
    dict overrides per name)."""

    def __init__(self, ratio: float = 0.5,
                 ratios: Optional[Dict[str, float]] = None):
        self.ratio = float(ratio)
        self.ratios = dict(ratios or {})

    def ratio_for(self, name: str) -> float:
        return float(self.ratios.get(name, self.ratio))

    def mask(self, value, name: str = ""):
        import jax.numpy as jnp

        r = self.ratio_for(name)
        if r <= 0:
            return jnp.ones_like(value)
        k = int(np.floor(value.size * r))
        if k <= 0:
            return jnp.ones_like(value)
        flat = jnp.abs(value).reshape(-1)
        # threshold = k-th smallest magnitude (inclusive): exactly k
        # entries prune when magnitudes are distinct
        thresh = jnp.sort(flat)[k - 1]
        return (jnp.abs(value) > thresh).astype(value.dtype)


# ---------------------------------------------------------------------------
# Strategies + compress pass
# ---------------------------------------------------------------------------

class Strategy:
    """Event-hook base (reference core/strategy.py): override any of
    the on_* callbacks; active inside [start_epoch, end_epoch)."""

    def __init__(self, start_epoch: int = 0, end_epoch: int = 10):
        self.start_epoch = int(start_epoch)
        self.end_epoch = int(end_epoch)

    def on_compress_begin(self, context):
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compress_end(self, context):
        pass


class SlimContext:
    """Compression state handed to strategy hooks (reference
    core/compress_pass.py Context)."""

    def __init__(self, exe, program, scope):
        self.exe = exe
        self.program = program
        self.scope = scope
        self.epoch = 0
        self.epoch_id = 0
        self.batch_id = 0
        self.last_fetch = None


class PruneStrategy(Strategy):
    """Iterative magnitude pruning (reference
    prune/prune_strategy.py:38): every `frequency` batches inside the
    active window, recompute masks and zero the pruned weights — the
    optimizer may revive them between triggers, the re-application
    keeps them dead, and after end_epoch the final masks are pinned via
    on_compress_end."""

    def __init__(self, pruner: Pruner, params: Optional[Sequence[str]]
                 = None, mini_batch_pruning_frequency: int = 1,
                 start_epoch: int = 0, end_epoch: int = 10):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner
        self.params = list(params) if params is not None else None
        self.frequency = max(1, int(mini_batch_pruning_frequency))
        self.masks: Dict[str, object] = {}

    def _target_params(self, context) -> List[str]:
        if self.params is not None:
            return self.params
        return [v.name for v in context.program.list_vars()
                if isinstance(v, Parameter)]

    def apply_masks(self, context):
        """Recompute masks from current magnitudes and zero the pruned
        entries in the scope (device-side multiply)."""
        for name in self._target_params(context):
            val = context.scope.find_var(name)
            if val is None:
                continue
            m = self.pruner.mask(val, name)
            self.masks[name] = m
            context.scope.set_var(name, val * m)

    def reapply(self, context):
        """Re-zero with the LAST computed masks (no recompute) — used
        after optimizer steps once pruning has converged."""
        for name, m in self.masks.items():
            val = context.scope.find_var(name)
            if val is not None:
                context.scope.set_var(name, val * m)

    def on_batch_end(self, context):
        if not (self.start_epoch <= context.epoch_id < self.end_epoch):
            return
        if context.batch_id % self.frequency == 0:
            self.apply_masks(context)
        else:
            self.reapply(context)

    def on_compress_end(self, context):
        self.reapply(context)


class CompressPass:
    """Drive a training loop while strategies compress the model
    (reference core/compress_pass.py CompressPass.apply/run).

    reader: callable -> iterable of feed dicts; fetch_list: vars to
    fetch per batch (last fetch lands in context.last_fetch)."""

    def __init__(self, executor, program=None, scope=None,
                 strategies: Optional[Sequence[Strategy]] = None):
        self.exe = executor
        self.program = program or default_main_program()
        self.scope = scope or global_scope()
        self.strategies = list(strategies or [])

    def add_strategy(self, strategy: Strategy):
        self.strategies.append(strategy)
        return self

    def run(self, reader: Callable, epochs: int,
            fetch_list: Optional[Sequence] = None,
            event_handler: Optional[Callable] = None):
        ctx = SlimContext(self.exe, self.program, self.scope)
        ctx.epoch = epochs
        for s in self.strategies:
            s.on_compress_begin(ctx)
        for epoch in range(epochs):
            ctx.epoch_id = epoch
            for s in self.strategies:
                s.on_epoch_begin(ctx)
            for batch_id, feed in enumerate(reader()):
                ctx.batch_id = batch_id
                for s in self.strategies:
                    s.on_batch_begin(ctx)
                ctx.last_fetch = self.exe.run(
                    self.program, feed=feed,
                    fetch_list=list(fetch_list or []))
                for s in self.strategies:
                    s.on_batch_end(ctx)
                if event_handler:
                    event_handler(ctx)
            for s in self.strategies:
                s.on_epoch_end(ctx)
        for s in self.strategies:
            s.on_compress_end(ctx)
        return ctx


def sparsity(scope=None, params: Optional[Sequence[str]] = None,
             program=None) -> float:
    """Fraction of exactly-zero entries across the given params (all
    Parameters by default) — the measurement the reference's pruning
    demos report."""
    scope = scope or global_scope()
    if params is None:
        program = program or default_main_program()
        params = [v.name for v in program.list_vars()
                  if isinstance(v, Parameter)]
    zeros = total = 0
    for name in params:
        val = scope.find_var(name)
        if val is None:
            continue
        arr = np.asarray(val)
        zeros += int((arr == 0).sum())
        total += arr.size
    return zeros / max(total, 1)


# ---------------------------------------------------------------------------
# Distillation
# ---------------------------------------------------------------------------

def distillation_loss(student_logits, teacher_logits, temperature=2.0,
                      hard_loss=None, soft_weight=0.7):
    """Hinton soft-target distillation loss, composed in-graph.

    L = soft_weight * T^2 * KL(softmax(t/T) || softmax(s/T))
        + (1 - soft_weight) * hard_loss

    The T^2 factor keeps soft-gradient magnitudes comparable across
    temperatures (Hinton et al., 2015).  teacher_logits should come
    from a frozen teacher branch (build it under stop_gradient or a
    separate for_test program).  reference analog: contrib/slim's
    distillation strategies (the framework shipped the pass plumbing;
    the loss is the standard one)."""
    from .. import layers

    t = float(temperature)
    s_scaled = layers.scale(student_logits, scale=1.0 / t)
    t_scaled = layers.scale(teacher_logits, scale=1.0 / t)
    # KL(teacher || student) = sum p_t * (log p_t - log p_s); the
    # log p_t entropy term is constant w.r.t. the student but keeps the
    # reported loss >= 0 and -> 0 at a perfect match
    p_t = layers.softmax(t_scaled)
    log_p_t = layers.log_softmax(t_scaled)
    log_p_s = layers.log_softmax(s_scaled)
    kl = layers.reduce_sum(
        layers.elementwise_mul(
            p_t, layers.elementwise_sub(log_p_t, log_p_s)),
        dim=[-1])
    soft = layers.scale(layers.reduce_mean(kl), scale=t * t)
    if hard_loss is None:
        return soft
    w = float(soft_weight)
    return layers.elementwise_add(
        layers.scale(soft, scale=w),
        layers.scale(hard_loss, scale=1.0 - w))
