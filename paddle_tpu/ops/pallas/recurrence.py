"""Blocked fused LSTM recurrence kernel (Pallas, TPU).

The scan-bound story (BENCH_r05: LSTM 0.078 MFU, nowhere near any
roofline): `lax.scan` lowers one XLA while-iteration per timestep, so
every step pays loop bookkeeping, an HBM round-trip for the (N, H)
carry, and a dynamic-slice/dynamic-update-slice pair on the stacked
(T, ...) tensors — the per-step recurrent GEMM (N×H @ H×4H) is far too
small to hide any of it.  The reference framework ships fused
recurrence as first-class capability (`fusion_lstm` / `fusion_gru` /
`cudnn_lstm`, paddle/fluid/operators/fused/fusion_lstm_op.cc); this
kernel is the TPU analog, with the same blocked-kernel discipline as
ops/pallas/flash_attention.py:

- ONE grid step covers a whole block of T_BLOCK timesteps: the carry
  (h, c) lives in f32 VMEM scratch across the entire sequence (grid
  steps run sequentially on a TPU core, so scratch persists), the
  x-slab for the block streams HBM→VMEM once, and the small recurrent
  GEMM fuses with the gate elementwise per step — no per-step HBM
  carry traffic, no while-loop bookkeeping.
- seq_len masking freezes the carry past each row's end (identical
  semantics to the scan path in ops/rnn.py); `is_reverse` is handled
  by flipping the time axis outside and adjusting the validity
  predicate for the zero-padded tail inside.
- custom VJP: the backward re-runs the gate math per block from the
  saved (h, c) sequences (flash-attention-style recompute — the
  (N, T, 4H) gate tensor is never materialized in HBM), accumulating
  dW in VMEM scratch and carrying (dh, dc) backward through the grid.

Gate layout matches ops/rnn.py `dynamic_lstm` exactly:
[candidate, input, forget, output] with sigmoid gates / tanh cell and
candidate.  Peepholes, nested (lod2) inputs, and non-default
activations are rejected LOUDLY (the backward derivatives are
hand-derived for sigmoid/tanh) — callers fall back to the scan path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Time-block default — lives ONLY here (CLAUDE.md VMEM lesson: a stale
# fallback at a call site silently overrides a retune).  VMEM budget at
# the bench shape (N=128, H=512, f32): the x-slab is N*4H*4B = 1 MB per
# timestep and Pallas double-buffers it, the hs/cs out-slabs are 256 KB
# per step each (double-buffered), and W is 4 MB — so block_t=4 keeps
# the working set ~(2*4 + 2*2*1 + 4 + 0.5) ≈ 16 MB.  UNTUNED on a real
# chip (no chip contact this round); retune here, nowhere else.
DEFAULT_BLOCK_T = 4

# -- kernel cost registry (observe/cost.py injects these at the custom
# -- call instructions) ------------------------------------------------
#
# Dense-equivalent convention (flash_attention.py precedent): the flop
# count of the logical math the scan composition computes ONCE —
# backward gate recompute is NOT credited.  Per timestep, N rows, H
# hidden:
#   fwd: gates = h @ W            -> 2*N*H*4H
#   bwd: dh = dg W^T, dW += h^T dg -> 4*N*H*4H
# Per-cell constants cover the gate elementwise work as XLA counts it
# in the scan composition (adds/muls/selects; sigmoid/tanh land under
# transcendentals in both accountings).
_LSTM_FWD_PER_CELL = 10.0
_LSTM_BWD_PER_CELL = 22.0


def _lstm_dims(operand_shapes):
    (t, n, g4) = operand_shapes[0][0]
    return t, n, g4 // 4, g4


def lstm_fwd_cost(operand_shapes, result_shapes):
    t, n, h, g4 = _lstm_dims(operand_shapes)
    flops = t * n * (2.0 * h * g4 + _LSTM_FWD_PER_CELL * h)
    return flops, None  # bytes: default materialized-buffers model


def lstm_bwd_cost(operand_shapes, result_shapes):
    t, n, h, g4 = _lstm_dims(operand_shapes)
    flops = t * n * (4.0 * h * g4 + _LSTM_BWD_PER_CELL * h)
    return flops, None


def _register_costs():
    from . import register_kernel_cost

    register_kernel_cost("lstm_fwd", lstm_fwd_cost)
    register_kernel_cost("lstm_bwd", lstm_bwd_cost)


_register_costs()


def _pallas_call(*args, **kw):
    from . import pallas_call  # shared interpret gate (package init)

    return pallas_call(*args, **kw)


def _valid(tidx, sl, t_true, rev):
    """(N, 1) mask: does original timestep `tidx` advance row state?
    Work domain is the (possibly flipped, zero-padded-to-block) time
    axis; `sl` is (N, 1) int32.  Padded tail steps (tidx >= t_true)
    must freeze the carry in BOTH directions or h_last drifts."""
    if rev:
        # work step tidx is original step (t_true - 1 - tidx)
        return jnp.logical_and(tidx < t_true, (t_true - 1 - tidx) < sl)
    return tidx < jnp.minimum(sl, t_true)


def _split_gates(gates):
    h = gates.shape[1] // 4
    # dynamic_lstm layout (lstm_op.cc): candidate, input, forget, output
    return (gates[:, :h], gates[:, h:2 * h], gates[:, 2 * h:3 * h],
            gates[:, 3 * h:])


def _fwd_kernel(x_ref, w_ref, h0_ref, c0_ref, sl_ref, hs_ref, cs_ref,
                h_scr, c_scr, *, block_t, t_true, rev):
    from jax.experimental import pallas as pl

    tb = pl.program_id(0)

    @pl.when(tb == 0)
    def _init():
        h_scr[:] = h0_ref[...].astype(jnp.float32)
        c_scr[:] = c0_ref[...].astype(jnp.float32)

    w = w_ref[...]
    sl = sl_ref[...]  # (N, 1) int32
    for k in range(block_t):  # static unroll: all indexing stays static
        tidx = tb * block_t + k
        h, c = h_scr[:], c_scr[:]
        gates = x_ref[k].astype(jnp.float32) + jax.lax.dot_general(
            h.astype(w.dtype), w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        cand, ig, fg, og = _split_gates(gates)
        i = jax.nn.sigmoid(ig)
        f = jax.nn.sigmoid(fg)
        c_new = f * c + i * jnp.tanh(cand)
        h_new = jax.nn.sigmoid(og) * jnp.tanh(c_new)
        ok = _valid(tidx, sl, t_true, rev)
        h_scr[:] = jnp.where(ok, h_new, h)
        c_scr[:] = jnp.where(ok, c_new, c)
        hs_ref[k] = h_scr[:].astype(hs_ref.dtype)
        cs_ref[k] = c_scr[:].astype(cs_ref.dtype)


def _bwd_kernel(x_ref, w_ref, hp_ref, cp_ref, sl_ref, dhs_ref, dcs_ref,
                dx_ref, dw_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, dw_scr, *, block_t, t_true, rev):
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    ng = pl.num_programs(0)
    tb = ng - 1 - g  # grid runs time blocks in REVERSE

    @pl.when(g == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dw_scr[:] = jnp.zeros_like(dw_scr)

    w = w_ref[...]
    sl = sl_ref[...]
    for k in range(block_t - 1, -1, -1):
        tidx = tb * block_t + k
        x_t = x_ref[k].astype(jnp.float32)
        h_prev = hp_ref[k].astype(jnp.float32)
        c_prev = cp_ref[k].astype(jnp.float32)
        # recompute the gates for this step (never stored in HBM)
        gates = x_t + jax.lax.dot_general(
            h_prev.astype(w.dtype), w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        cand, ig, fg, og = _split_gates(gates)
        i = jax.nn.sigmoid(ig)
        f = jax.nn.sigmoid(fg)
        o = jax.nn.sigmoid(og)
        ca = jnp.tanh(cand)
        c_new = f * c_prev + i * ca
        tc = jnp.tanh(c_new)

        dh_tot = dhs_ref[k].astype(jnp.float32) + dh_scr[:]
        # a frozen row's h_out is h_prev itself: its dh must NOT fold
        # into the cell cotangent through o*tanh'(c)
        dc_pass = dcs_ref[k].astype(jnp.float32) + dc_scr[:]
        dc_tot = dc_pass + dh_tot * o * (1.0 - tc * tc)
        dpre_o = (dh_tot * tc) * o * (1.0 - o)
        dpre_i = (dc_tot * ca) * i * (1.0 - i)
        dpre_f = (dc_tot * c_prev) * f * (1.0 - f)
        dpre_c = (dc_tot * i) * (1.0 - ca * ca)
        dg = jnp.concatenate([dpre_c, dpre_i, dpre_f, dpre_o], axis=1)
        ok = _valid(tidx, sl, t_true, rev)
        # frozen steps pass state (and its cotangent) straight through
        dg = jnp.where(ok, dg, 0.0)
        dx_ref[k] = dg.astype(dx_ref.dtype)
        dh_prev = jax.lax.dot_general(
            dg.astype(w.dtype), w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dh_scr[:] = jnp.where(ok, dh_prev, dh_tot)
        dc_scr[:] = jnp.where(ok, dc_tot * f, dc_pass)
        # dg rows are already zeroed for frozen/padded steps, so their
        # h_prev rows contribute nothing to dW
        dw_scr[:] += jax.lax.dot_general(
            h_prev, dg, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(g == ng - 1)
    def _fin():
        dh0_ref[...] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_scr[:].astype(dc0_ref.dtype)
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)


def _fwd_call(xs, w, h0, c0, sl, t_true, rev, block_t):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_pad, n, g4 = xs.shape
    h_dim = g4 // 4
    grid = (t_pad // block_t,)
    return _pallas_call(
        functools.partial(_fwd_kernel, block_t=block_t, t_true=t_true,
                          rev=rev),
        name="lstm_fwd",
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, n, g4), lambda tb: (tb, 0, 0)),
            pl.BlockSpec((h_dim, g4), lambda tb: (0, 0)),
            pl.BlockSpec((n, h_dim), lambda tb: (0, 0)),
            pl.BlockSpec((n, h_dim), lambda tb: (0, 0)),
            pl.BlockSpec((n, 1), lambda tb: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, n, h_dim), lambda tb: (tb, 0, 0)),
            pl.BlockSpec((block_t, n, h_dim), lambda tb: (tb, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_pad, n, h_dim), xs.dtype),
            jax.ShapeDtypeStruct((t_pad, n, h_dim), xs.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((n, h_dim), jnp.float32)] * 2,
    )(xs, w, h0, c0, sl)


def _bwd_call(xs, w, hp, cp, sl, dhs, dcs, t_true, rev, block_t):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_pad, n, g4 = xs.shape
    h_dim = g4 // 4
    nt = t_pad // block_t

    def tblock(g):
        return (nt - 1 - g, 0, 0)

    return _pallas_call(
        functools.partial(_bwd_kernel, block_t=block_t, t_true=t_true,
                          rev=rev),
        name="lstm_bwd",
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((block_t, n, g4), tblock),
            pl.BlockSpec((h_dim, g4), lambda g: (0, 0)),
            pl.BlockSpec((block_t, n, h_dim), tblock),
            pl.BlockSpec((block_t, n, h_dim), tblock),
            pl.BlockSpec((n, 1), lambda g: (0, 0)),
            pl.BlockSpec((block_t, n, h_dim), tblock),
            pl.BlockSpec((block_t, n, h_dim), tblock),
        ],
        out_specs=[
            pl.BlockSpec((block_t, n, g4), tblock),
            pl.BlockSpec((h_dim, g4), lambda g: (0, 0)),
            pl.BlockSpec((n, h_dim), lambda g: (0, 0)),
            pl.BlockSpec((n, h_dim), lambda g: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_pad, n, g4), xs.dtype),
            jax.ShapeDtypeStruct((h_dim, g4), w.dtype),
            jax.ShapeDtypeStruct((n, h_dim), hp.dtype),
            jax.ShapeDtypeStruct((n, h_dim), cp.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, h_dim), jnp.float32),
            pltpu.VMEM((n, h_dim), jnp.float32),
            pltpu.VMEM((h_dim, g4), jnp.float32),
        ],
    )(xs, w, hp, cp, sl, dhs, dcs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _lstm(xs, w, h0, c0, sl, t_true, rev, block_t):
    return _fwd_call(xs, w, h0, c0, sl, t_true, rev, block_t)


def _lstm_vjp_fwd(xs, w, h0, c0, sl, t_true, rev, block_t):
    hs, cs = _fwd_call(xs, w, h0, c0, sl, t_true, rev, block_t)
    return (hs, cs), (xs, w, h0, c0, sl, hs, cs)


def _lstm_vjp_bwd(t_true, rev, block_t, res, cts):
    xs, w, h0, c0, sl, hs, cs = res
    dhs, dcs = cts
    # per-step previous states from the saved sequences (padded tail
    # entries hold the frozen carry — finite, and their dg is masked)
    hp = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]], axis=0)
    cp = jnp.concatenate([c0[None].astype(cs.dtype), cs[:-1]], axis=0)
    dxs, dw, dh0, dc0 = _bwd_call(xs, w, hp, cp, sl,
                                  dhs.astype(hs.dtype),
                                  dcs.astype(cs.dtype),
                                  t_true, rev, block_t)
    return dxs, dw, dh0.astype(h0.dtype), dc0.astype(c0.dtype), None


_lstm.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)


def fused_lstm(x, w, h0=None, c0=None, seq_len=None, *,
               is_reverse=False, use_peepholes=False,
               gate_activation="sigmoid", cell_activation="tanh",
               candidate_activation="tanh", block_t=None):
    """Fused multi-timestep LSTM over a pre-projected, bias-added input.

    x: (N, T, 4H) — `x @ W_x + b` done by the caller (the dynamic_lstm
    contract); w: (H, 4H) recurrent weights; h0/c0: optional (N, H)
    initial states; seq_len: optional (N,) int lengths (state freezes
    past each row's end, matching the scan path bit-for-bit semantics).

    Returns (hidden (N, T, H), cell (N, T, H), last_h (N, H),
    last_c (N, H)).  Differentiable w.r.t. x, w, h0, c0 via a custom
    VJP that recomputes gates per time block.
    """
    if use_peepholes:
        raise ValueError(
            "fused_lstm (Pallas recurrence kernel) does not support "
            "peepholes — use the scan path (use_pallas=False)")
    acts = (gate_activation, cell_activation, candidate_activation)
    if acts != ("sigmoid", "tanh", "tanh"):
        raise ValueError(
            f"fused_lstm supports only (sigmoid, tanh, tanh) "
            f"activations, got {acts} — the fused backward derivatives "
            f"are hand-derived; use the scan path (use_pallas=False)")
    n, t, g4 = x.shape
    if g4 % 4:
        raise ValueError(f"fused_lstm: input width {g4} is not 4*H")
    h_dim = g4 // 4
    block_t = DEFAULT_BLOCK_T if block_t is None else int(block_t)
    block_t = max(1, min(block_t, t))
    if h0 is None:
        h0 = jnp.zeros((n, h_dim), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((n, h_dim), x.dtype)
    sl = (seq_len if seq_len is not None
          else jnp.full((n,), t, jnp.int32))
    sl = sl.astype(jnp.int32).reshape(n, 1)

    xs = jnp.swapaxes(x, 0, 1)  # (T, N, 4H) time-major
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
    t_pad = -(-t // block_t) * block_t
    if t_pad != t:
        xs = jnp.pad(xs, ((0, t_pad - t), (0, 0), (0, 0)))
    hs, cs = _lstm(xs, w, h0, c0, sl, t, bool(is_reverse),
                   int(block_t))
    hs, cs = hs[:t], cs[:t]
    # the carry freezes past seq ends, so the last work-domain step IS
    # the final state (identical to the scan path's final carry)
    h_last, c_last = hs[-1], cs[-1]
    if is_reverse:
        hs = jnp.flip(hs, axis=0)
        cs = jnp.flip(cs, axis=0)
    return (jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1),
            h_last, c_last)
