"""Resilience subsystem (ISSUE 4): every recovery behavior is proven by
injecting its fault (resilience/chaos.py) —

- the in-step update guard skips EXACTLY the poisoned step (parameters
  thereafter match a run that never saw that batch) with zero extra
  dispatches/retraces vs an unguarded step (the one-jitted-step
  invariant),
- dynamic loss scaling halves on overflow and recovers after N good
  steps, surviving telemetry-window resets,
- torn checkpoints (death between shard write and manifest write, via
  the `ckpt:before_manifest` failpoint) are NEVER loadable — the
  CLAUDE.md manifest-last claim, finally tested — and resume picks the
  prior serial,
- a corrupt shard fails CRC with a structured CheckpointError and the
  Trainer falls back to the newest VALID serial (logged, not
  swallowed),
- the serving circuit breaker opens/half-opens/closes
  deterministically; all rejections are structured dicts,
- the watchdog fires on an injected hang; retry backoff is
  deterministic.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observe, resilience
from paddle_tpu.contrib import CheckpointConfig, Trainer
from paddle_tpu.resilience import chaos
from paddle_tpu.serving import (AdmissionController, CircuitBreaker,
                                CircuitOpenError)


@pytest.fixture(autouse=True)
def _clear_failpoints():
    yield
    chaos.clear()


def _linreg_program():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, scope, loss


def _batches(n, seed=7, bs=8):
    rng = np.random.RandomState(seed)
    return [{"x": rng.rand(bs, 4).astype(np.float32),
             "y": rng.rand(bs, 1).astype(np.float32)}
            for _ in range(n)]


def _persistables(main):
    return {v.name: np.asarray(fluid.global_scope().find_var(v.name))
            for v in main.list_vars() if v.persistable}


# ---------------------------------------------------------------------------
# In-step update guard
# ---------------------------------------------------------------------------

def test_guard_skips_exactly_the_poisoned_step():
    batches = _batches(4)
    poisoned = chaos.poison_feed(batches[2], names=["x"])

    # reference: a run that never saw the poisoned batch
    main, startup, scope, loss = _linreg_program()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for b in (batches[0], batches[1], batches[3]):
            exe.run(main, feed=b, fetch_list=[loss])
        ref = _persistables(main)

    # guarded run: same stream WITH the poison in the middle
    main2, startup2, scope2, loss2 = _linreg_program()
    resilience.enable_update_guard(main2)
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor()
        exe2.run(startup2)
        for b in (batches[0], batches[1], poisoned, batches[3]):
            exe2.run(main2, feed=b, fetch_list=[loss2])
        got = _persistables(main2)
    tel = observe.fetch_telemetry(scope2)
    assert tel.steps == 4
    assert tel.skipped_update_steps == 1
    assert tel.nonfinite_grad_steps == 1
    for name, want in ref.items():
        assert np.isfinite(got[name]).all(), name
        np.testing.assert_allclose(got[name], want, rtol=1e-6,
                                   atol=1e-7, err_msg=name)


def test_unguarded_program_is_corrupted_by_the_same_poison():
    """The guard is the difference: without it, one NaN batch destroys
    every parameter (the failure mode the ISSUE names)."""
    batches = _batches(2)
    main, startup, scope, loss = _linreg_program()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=chaos.poison_feed(batches[0], names=["x"]),
                fetch_list=[loss])
        got = _persistables(main)
    assert any(not np.isfinite(v).all() for v in got.values())


def test_guard_adds_no_dispatches_retraces_or_callbacks():
    """Acceptance criterion: runtime_stats counters for a guarded step
    match an unguarded step — the guard lives INSIDE the one jitted
    computation."""
    batches = _batches(2)

    def run_and_count(guard):
        main, startup, scope, loss = _linreg_program()
        if guard:
            resilience.enable_update_guard(main)
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            snap = observe.runtime_stats.snapshot()
            for b in batches:
                exe.run(main, feed=b, fetch_list=[loss])
            delta = observe.runtime_stats.delta(snap)
            fn, state, feeds = exe._prepare(
                main, batches[0], [loss.name], scope, 1, True)
            text = fn.lower(state, feeds).as_text()
        return delta, text

    unguarded, _ = run_and_count(False)
    guarded, lowered = run_and_count(True)
    assert guarded["dispatches"] == unguarded["dispatches"]
    assert guarded["retraces"] == unguarded["retraces"] == 0
    assert "callback" not in lowered  # no host round-trips


def test_guard_composes_with_chained_iterations():
    """K chained steps with a guard still accumulate correctly (the
    guard state rides the fori_loop carry)."""
    batches = _batches(1)
    main, startup, scope, loss = _linreg_program()
    resilience.enable_update_guard(main)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=batches[0], fetch_list=[loss], iterations=4)
    tel = observe.fetch_telemetry(scope)
    assert tel.steps == 4
    assert tel.skipped_update_steps == 0


# ---------------------------------------------------------------------------
# Dynamic loss scaling
# ---------------------------------------------------------------------------

def _scaled_program(init_scale=8.0, incr_every=2):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.amp.decorate(
            fluid.optimizer.SGDOptimizer(learning_rate=0.1),
            use_dynamic_loss_scaling=True,
            init_loss_scaling=init_scale,
            incr_every_n_steps=incr_every)
        opt.minimize(loss)
    return main, startup, scope, loss


def test_loss_scale_halves_on_overflow_and_recovers():
    batches = _batches(3)
    main, startup, scope, loss = _scaled_program(init_scale=8.0,
                                                 incr_every=2)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=chaos.poison_feed(batches[0], names=["x"]),
                fetch_list=[loss])
        tel = observe.fetch_telemetry(scope, reset=False)
        assert tel.loss_scale == 4.0          # halved on overflow
        assert tel.skipped_update_steps == 1
        exe.run(main, feed=batches[1], fetch_list=[loss])
        exe.run(main, feed=batches[2], fetch_list=[loss])
    tel = observe.fetch_telemetry(scope)
    assert tel.loss_scale == 8.0              # doubled after 2 good
    assert tel.skipped_update_steps == 1


def test_loss_scaled_updates_match_unscaled_amp_run():
    """Scaling is numerically transparent: the scale is a power of two
    (exact exponent shift) and grads are unscaled before the optimizer,
    so an amp run WITH dynamic scaling matches the same amp run WITHOUT
    it on clean data (the only delta is the scale machinery)."""
    batches = _batches(3, seed=11)

    def amp_run(use_scaling):
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            x = layers.data(name="x", shape=[4], dtype="float32")
            y = layers.data(name="y", shape=[1], dtype="float32")
            pred = layers.fc(x, size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            opt = fluid.amp.decorate(
                fluid.optimizer.MomentumOptimizer(learning_rate=0.1,
                                                  momentum=0.9),
                use_dynamic_loss_scaling=use_scaling,
                init_loss_scaling=1024.0)
            opt.minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            for b in batches:
                exe.run(main, feed=b, fetch_list=[loss])
            return _persistables(main)

    ref = amp_run(False)
    got = amp_run(True)
    for name, want in ref.items():
        np.testing.assert_allclose(got[name], want, rtol=1e-5,
                                   atol=1e-6, err_msg=name)


def test_loss_scale_survives_telemetry_window_reset():
    batches = _batches(1)
    main, startup, scope, loss = _scaled_program(init_scale=8.0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=chaos.poison_feed(batches[0], names=["x"]),
                fetch_list=[loss])
        assert observe.fetch_telemetry(scope).loss_scale == 4.0
        # the reset above zeroed window counters but kept the schedule
        tel = observe.fetch_telemetry(scope, reset=False)
        assert tel.loss_scale == 4.0
        assert tel.steps == 0


# ---------------------------------------------------------------------------
# Checkpoint integrity
# ---------------------------------------------------------------------------

def _build_ckpt(tmp_path, train_steps=2):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    ckpt = str(tmp_path / "ck")
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        for b in _batches(train_steps):
            exe.run(main, feed=b, fetch_list=[loss])
        fluid.io.save_sharded(exe, ckpt, main_program=main)
    return main, scope, exe, ckpt


def test_missing_manifest_is_structured_not_raw(tmp_path):
    main, scope, exe, _ = _build_ckpt(tmp_path)
    with pytest.raises(resilience.CheckpointNotFoundError) as ei:
        with fluid.scope_guard(scope):
            fluid.io.load_sharded(exe, str(tmp_path / "nowhere"),
                                  main_program=main)
    d = ei.value.as_dict()
    assert d["error"] == "checkpoint_not_found"
    assert "nowhere" in d["dirname"]


def test_corrupt_shard_fails_verification(tmp_path):
    main, scope, exe, ckpt = _build_ckpt(tmp_path)
    chaos.corrupt_shard(ckpt, mode="flip")
    with pytest.raises(resilience.CheckpointCorruptError) as ei:
        with fluid.scope_guard(scope):
            fluid.io.load_sharded(exe, ckpt, main_program=main)
    assert ei.value.as_dict()["error"] == "checkpoint_corrupt"


def test_truncated_shard_fails_verification(tmp_path):
    main, scope, exe, ckpt = _build_ckpt(tmp_path)
    chaos.corrupt_shard(ckpt, mode="truncate")
    with pytest.raises(resilience.CheckpointCorruptError):
        with fluid.scope_guard(scope):
            fluid.io.load_sharded(exe, ckpt, main_program=main)


def test_garbage_manifest_is_corrupt_not_json_error(tmp_path):
    main, scope, exe, ckpt = _build_ckpt(tmp_path)
    with open(os.path.join(ckpt, fluid.io.SHARD_MANIFEST), "w") as f:
        f.write("{ not json")
    with pytest.raises(resilience.CheckpointCorruptError):
        with fluid.scope_guard(scope):
            fluid.io.load_sharded(exe, ckpt, main_program=main)


def test_newer_format_version_is_structured(tmp_path):
    main, scope, exe, ckpt = _build_ckpt(tmp_path)
    mpath = os.path.join(ckpt, fluid.io.SHARD_MANIFEST)
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["version"] = 10 ** 6
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(resilience.CheckpointFormatError):
        with fluid.scope_guard(scope):
            fluid.io.load_sharded(exe, ckpt, main_program=main)


def test_combined_format_missing_manifest_structured(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[4], dtype="float32")
        layers.fc(x, size=1)
        exe = fluid.Executor()
        exe.run(startup)
        with pytest.raises(resilience.CheckpointNotFoundError):
            fluid.io.load_persistables(exe, str(tmp_path / "empty"),
                                       main_program=main)


def test_combined_format_crc_roundtrip_and_corruption(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    d = str(tmp_path / "plain")
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[4], dtype="float32")
        layers.fc(x, size=1)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_persistables(exe, d, main_program=main)
        fluid.io.load_persistables(exe, d, main_program=main)  # clean
        chaos.corrupt_file(os.path.join(d, "params.npz"))
        with pytest.raises(resilience.CheckpointCorruptError):
            fluid.io.load_persistables(exe, d, main_program=main)


# ---------------------------------------------------------------------------
# Trainer fallback (torn + corrupt) — the CLAUDE.md manifest-last claim
# ---------------------------------------------------------------------------

def _train_func():
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    return layers.mean(layers.square_error_cost(pred, y))


def _opt_func():
    return fluid.optimizer.SGDOptimizer(learning_rate=0.1)


def _reader(n=6):
    def read():
        r = np.random.RandomState(3)
        for _ in range(n):
            yield {"x": r.rand(8, 4).astype(np.float32),
                   "y": r.rand(8, 1).astype(np.float32)}
    return read


def test_torn_checkpoint_never_loadable_resume_picks_prior(tmp_path):
    """Simulated death BETWEEN shard write and manifest write (the
    chaos failpoint io.save_sharded calls at exactly that spot): the
    partial directory must never be considered loadable, and a
    restarted Trainer resumes from the prior serial."""
    ckpt_dir = str(tmp_path / "ck")
    log = str(tmp_path / "ev.jsonl")
    t = Trainer(_train_func, _opt_func,
                checkpoint_config=CheckpointConfig(ckpt_dir,
                                                   step_interval=2),
                telemetry=observe.TelemetryConfig(interval=100,
                                                  log_path=log))
    t.train(num_epochs=1, reader=_reader())
    ids = t._list_checkpoints()
    assert ids, "no checkpoints saved"
    last_good = ids[-1]

    chaos.arm("ckpt:before_manifest")
    with pytest.raises(chaos.ChaosKilled):
        t._save_checkpoint(last_good + 1, 0, 99)
    torn = os.path.join(ckpt_dir, f"ckpt_{last_good + 1}")
    assert os.path.isdir(torn)  # shards were written...
    assert not os.path.exists(  # ...but the manifest never was
        os.path.join(torn, fluid.io.SHARD_MANIFEST))

    # the torn dir is invisible to checkpoint listing AND unloadable
    t2 = Trainer(_train_func, _opt_func,
                 checkpoint_config=CheckpointConfig(ckpt_dir,
                                                    step_interval=2),
                 telemetry=observe.TelemetryConfig(interval=100,
                                                   log_path=log))
    assert t2._list_checkpoints()[-1] == last_good
    with pytest.raises(resilience.CheckpointError):
        t2._load_checkpoint(torn)
    # resume landed on the last COMPLETE serial's cursor
    with open(os.path.join(ckpt_dir, f"ckpt_{last_good}",
                           "__trainer_state__.json")) as f:
        st = json.load(f)
    assert (t2._resume_epoch, t2._resume_step_in_epoch) \
        == (st["epoch"], st["step"])


def test_trainer_falls_back_over_corrupt_newest(tmp_path):
    ckpt_dir = str(tmp_path / "ck")
    log = str(tmp_path / "ev.jsonl")
    t = Trainer(_train_func, _opt_func,
                checkpoint_config=CheckpointConfig(ckpt_dir,
                                                   step_interval=2),
                telemetry=observe.TelemetryConfig(interval=100,
                                                  log_path=log))
    t.train(num_epochs=1, reader=_reader())
    ids = t._list_checkpoints()
    assert len(ids) >= 2, ids
    chaos.corrupt_shard(os.path.join(ckpt_dir, f"ckpt_{ids[-1]}"))

    t2 = Trainer(_train_func, _opt_func,
                 checkpoint_config=CheckpointConfig(ckpt_dir,
                                                    step_interval=2),
                 telemetry=observe.TelemetryConfig(interval=100,
                                                   log_path=log))
    events = observe.read_events(log)
    falls = [e for e in events if e["event"] == "ckpt_fallback"]
    assert falls and falls[-1]["serial"] == ids[-1]
    assert falls[-1]["error"]["error"] == "checkpoint_corrupt"
    resumes = [e for e in events if e["event"] == "ckpt_resume"]
    assert resumes and resumes[-1]["serial"] == ids[-2]
    assert resumes[-1]["fallback"] is True
    # the cursor is the fallback serial's, not the corrupt one's
    with open(os.path.join(ckpt_dir, f"ckpt_{ids[-2]}",
                           "__trainer_state__.json")) as f:
        st = json.load(f)
    assert t2._resume_step_in_epoch == st["step"]


def test_keep_last_k_retention(tmp_path):
    ckpt_dir = str(tmp_path / "ck")
    t = Trainer(_train_func, _opt_func,
                checkpoint_config=CheckpointConfig(
                    ckpt_dir, max_num_checkpoints=2, step_interval=1))
    t.train(num_epochs=1, reader=_reader(5))
    ids = t._list_checkpoints()
    assert len(ids) <= 2
    # newest serials survive the rotation
    assert ids == sorted(ids) and ids[-1] >= 4


# ---------------------------------------------------------------------------
# Circuit breaker (deterministic: injected clock)
# ---------------------------------------------------------------------------

def test_circuit_breaker_open_half_open_close():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=3, cooldown_s=10.0,
                        clock=lambda: now[0])
    assert br.state == br.CLOSED
    assert not br.record_failure()
    assert not br.record_failure()
    assert br.record_failure()          # threshold → OPEN
    assert br.state == br.OPEN
    assert not br.allow()               # cooldown not elapsed
    now[0] = 9.9
    assert not br.allow()
    now[0] = 10.0
    assert br.allow()                   # THE half-open probe
    assert br.state == br.HALF_OPEN
    assert not br.allow()               # concurrent submits still shed
    assert br.record_success()          # probe ok → CLOSED
    assert br.state == br.CLOSED
    assert br.opens == 1 and br.closes == 1


def test_circuit_breaker_failed_probe_reopens():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                        clock=lambda: now[0])
    assert br.record_failure()
    now[0] = 5.0
    assert br.allow()
    assert br.record_failure()          # probe failed → OPEN again
    assert br.state == br.OPEN
    assert not br.allow()               # fresh cooldown from reopen
    now[0] = 9.9
    assert not br.allow()
    now[0] = 10.0
    assert br.allow()


def test_admission_degraded_rejections_are_structured():
    now = [0.0]
    adm = AdmissionController(
        queue_capacity=4,
        breaker=CircuitBreaker(failure_threshold=2, cooldown_s=5.0,
                               clock=lambda: now[0]))
    adm.start()
    assert adm.record_dispatch_result(False) is None
    assert adm.record_dispatch_result(False) == "opened"
    assert adm.state == "degraded"
    with pytest.raises(CircuitOpenError) as ei:
        adm.check(inflight=0)
    d = ei.value.as_dict()
    assert d["error"] == "circuit_open"
    assert d["breaker"]["state"] == "open"
    assert d["retry_after_s"] == 5.0
    assert adm.health()["breaker"]["consecutive_failures"] == 2
    now[0] = 5.0
    adm.check(inflight=0)               # the half-open probe admits
    assert adm.record_dispatch_result(True) == "closed"
    assert adm.state == "running"
    # drain must work from DEGRADED too (rolling restart of a sick box)
    adm.record_dispatch_result(False)
    adm.record_dispatch_result(False)
    assert adm.state == "degraded"
    adm.begin_drain()
    assert adm.state == "draining"


# ---------------------------------------------------------------------------
# Watchdog + retry
# ---------------------------------------------------------------------------

def test_deadline_fires_on_injected_hang():
    with pytest.raises(resilience.WatchdogTimeout) as ei:
        with resilience.Deadline(1, what="chaos hang"):
            chaos.hang(10.0)
    d = ei.value.as_dict()
    assert d["error"] == "watchdog_timeout"
    assert d["what"] == "chaos hang"


def test_deadline_disabled_and_clean_exit():
    with resilience.Deadline(0, what="disabled"):
        pass
    with resilience.Deadline(60, what="fast"):
        x = 1 + 1
    assert x == 2


def test_retry_backoff_is_deterministic():
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    out = resilience.retry_call(flaky, retries=3, base_delay_s=0.1,
                                retry_on=(ConnectionError,),
                                sleep=sleeps.append)
    assert out == "ok"
    assert sleeps == [0.1, 0.2]


def test_retry_exhaustion_is_structured():
    sleeps = []
    with pytest.raises(resilience.RetriesExhaustedError) as ei:
        resilience.retry_call(
            lambda: (_ for _ in ()).throw(ConnectionError("down")),
            retries=2, base_delay_s=0.1, retry_on=(ConnectionError,),
            sleep=sleeps.append)
    d = ei.value.as_dict()
    assert d["attempts"] == 3
    assert "ConnectionError" in d["last_error"]
    assert sleeps == [0.1, 0.2]


def test_retry_does_not_catch_unlisted_exceptions():
    with pytest.raises(ValueError):
        resilience.retry_call(
            lambda: (_ for _ in ()).throw(ValueError("bug")),
            retries=5, retry_on=(ConnectionError,),
            sleep=lambda _s: None)
