"""Fused final-projection + label-smoothed softmax-CE Pallas kernel.

The big-vocab loss is the Transformer's HBM hot spot: composed, the
(N, V) logits tensor (N=B*T tokens, V≈32k vocab) materializes in f32 —
gigabytes of traffic per step between the projection matmul, the
softmax passes, and the backward.  This kernel never materializes
logits in HBM at all (the ops/jit/ tier of the reference,
kernel_base.h:25-44, is the precedent for owning hot kernels):

- forward: grid (token_blocks, vocab_blocks), vocab INNERMOST — the
  h-block and the online-softmax running stats (max, sumexp, target
  logit, logit sum) stay resident in VMEM while W streams through;
  per-token outputs are three f32 scalars (lse, z_label, z_sum).
  loss_i = lse_i - (1-eps) * z_label_i - (eps/V) * z_sum_i.
- backward: two accumulation kernels recomputing p = exp(z - lse)
  blockwise from the saved lse (flash-attention-style recompute):
  dh accumulates over vocab blocks (dh-block resident), dW over token
  blocks (dW-block resident).  dz = g * (p - (1-eps)*onehot - eps/V).

HBM traffic ≈ reads of h and W per pass instead of multiple (N, V)
round-trips; all matmuls are int-free MXU bf16 with f32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG = -1e30
# Block defaults — tuned ON the chip (r05, v5e, 32k vocab).  Two
# separate VMEM constraints bit here:
# 1. The (1, N) stat OUTPUTS (not the tiles) caused the original
#    compile failures at every block size — a degenerate sublane-1
#    layout that XLA stack-allocates in scoped VMEM ("exceeded scoped
#    vmem limit by 3.84M" regardless of blocks).  Fixed by the
#    8-sublane-replicated output layout in _fwd_kernel/_fwd.
# 2. The f32 logits tile + its mask/exp stack intermediates bound the
#    block product: (1024, 1024) and up fail Mosaic; (512, 1024)
#    compiles and measured fastest — bench sweep on chip:
#    (256,512) 0.3146 MFU < (512,512) 0.3204 ~ (1024,512) 0.3205
#    < (512,1024) 0.3250 (ties the unfused baseline at len256 and
#    beats it as part of the longctx stack: 0.3036 -> 0.3063, AB_r05.json).
DEFAULT_BLOCK_T = 512
DEFAULT_BLOCK_V = 1024

# -- kernel cost registry (observe/cost.py injects these at the custom
# -- call instructions) ------------------------------------------------
#
# Dense-equivalent convention (see flash_attention.py): flops the
# composed projection+CE would compute once, backward recompute of z
# NOT credited.  For N tokens, D hidden, V vocab:
#   fwd: z = h @ W                 -> 2*N*D*V
#   bwd: dh = dz W^T, dW = h^T dz  -> 4*N*D*V
# Per-logit constants cover the softmax/CE elementwise work as XLA
# counts it in the dense composition (measured: ~4.0 flops/logit fwd,
# ~3.0 bwd; exp lands under transcendentals in both accountings).
_CE_FWD_PER_LOGIT = 4.0
_CE_BWD_PER_LOGIT = 3.0


def _ce_dims(operand_shapes):
    (n, d) = operand_shapes[0][0]
    v = operand_shapes[1][0][1]
    return n, d, v


def _io_bytes(operand_shapes, result_shapes):
    total = 0
    for dims, elem in list(operand_shapes) + list(result_shapes):
        count = 1
        for d in dims:
            count *= d
        total += count * elem
    return float(total)


def vocab_ce_fwd_cost(operand_shapes, result_shapes):
    n, d, v = _ce_dims(operand_shapes)
    flops = n * v * (2.0 * d + _CE_FWD_PER_LOGIT)
    return flops, _io_bytes(operand_shapes, result_shapes)


def vocab_ce_dh_cost(operand_shapes, result_shapes):
    n, d, v = _ce_dims(operand_shapes)
    flops = n * v * (2.0 * d + 2.0 / 3.0 * _CE_BWD_PER_LOGIT)
    return flops, _io_bytes(operand_shapes, result_shapes)


def vocab_ce_dw_cost(operand_shapes, result_shapes):
    n, d, v = _ce_dims(operand_shapes)
    flops = n * v * (2.0 * d + 1.0 / 3.0 * _CE_BWD_PER_LOGIT)
    return flops, _io_bytes(operand_shapes, result_shapes)


def vocab_ce_cost(n_tokens, d, v, dtype_bytes=4):
    """Dense-equivalent (flops, bytes) of one fwd+bwd fused vocab-CE —
    the sum of the three kernels' registry entries (test/parity
    helper)."""
    h = ((n_tokens, d), dtype_bytes)
    w = ((d, v), dtype_bytes)
    lbl = ((1, n_tokens), 4)
    row = ((1, n_tokens), 4)
    stat = ((8, n_tokens), 4)
    fwd = vocab_ce_fwd_cost([h, w, lbl], [stat, stat, stat])
    dh = vocab_ce_dh_cost([h, w, lbl, row, row], [h])
    dw = vocab_ce_dw_cost([h, w, lbl, row, row], [w])
    return (fwd[0] + dh[0] + dw[0], fwd[1] + dh[1] + dw[1])


def _register_costs():
    from . import register_kernel_cost

    register_kernel_cost("vocab_ce_fwd", vocab_ce_fwd_cost)
    register_kernel_cost("vocab_ce_dh", vocab_ce_dh_cost)
    register_kernel_cost("vocab_ce_dw", vocab_ce_dw_cost)


_register_costs()


def _pallas_call(*args, **kw):
    from . import pallas_call  # shared interpret gate (package init)

    return pallas_call(*args, **kw)


def _z_block(h_ref, w_ref, vb, block_v, n_valid_v):
    """(block_t, block_v) logits for this tile, invalid vocab columns
    masked to NEG; returns (z, col_ids, valid_mask)."""
    z = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
    valid = col < n_valid_v
    return jnp.where(valid, z, NEG), col, valid


def _row_valid(tb, block_t, n_valid_t, shape):
    row = tb * block_t + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    return row < n_valid_t


def _fwd_kernel(h_ref, w_ref, lbl_ref, lse_ref, zt_ref, zsum_ref,
                m_scr, s_scr, zt_scr, zsum_scr, *, block_v, n_valid_v):
    from jax.experimental import pallas as pl

    vb = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG)
        s_scr[:] = jnp.zeros_like(s_scr)
        zt_scr[:] = jnp.full_like(zt_scr, NEG)
        zsum_scr[:] = jnp.zeros_like(zsum_scr)

    z, col, valid = _z_block(h_ref, w_ref, vb, block_v, n_valid_v)
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(z, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    s_scr[:] = s_scr[:] * alpha + jnp.sum(jnp.exp(z - m_new), axis=1,
                                          keepdims=True)
    m_scr[:] = m_new
    zsum_scr[:] = zsum_scr[:] + jnp.sum(jnp.where(valid, z, 0.0),
                                        axis=1, keepdims=True)
    hit = col == lbl_ref[...].reshape(-1, 1)
    zt_scr[:] = jnp.maximum(
        zt_scr[:], jnp.max(jnp.where(hit, z, NEG), axis=1,
                           keepdims=True))

    @pl.when(vb == nv - 1)
    def _fin():
        # stats replicated over 8 sublanes (same trick as
        # flash_attention's lse): a (1, n) output would carry a
        # degenerate T(1,128) sublane-1 layout that XLA:TPU stack-
        # allocates in scoped VMEM with 8x tile padding — the r05
        # on-chip compile failed with a scoped-vmem OOM on exactly
        # those three output buffers, at ANY block size
        lse = (m_scr[:] + jnp.log(s_scr[:]))[:, 0][None, :]
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        zt_ref[...] = jnp.broadcast_to(zt_scr[:][:, 0][None, :],
                                       zt_ref.shape)
        zsum_ref[...] = jnp.broadcast_to(zsum_scr[:][:, 0][None, :],
                                         zsum_ref.shape)


def _dz_block(h_ref, w_ref, lbl_ref, lse_ref, g_ref, tb, vb, *,
              block_t, block_v, n_valid_t, n_valid_v, eps):
    """Recomputed upstream-scaled logit gradient for this tile; padded
    token rows and vocab columns contribute exactly zero."""
    z, col, valid = _z_block(h_ref, w_ref, vb, block_v, n_valid_v)
    p = jnp.where(valid, jnp.exp(z - lse_ref[...].reshape(-1, 1)), 0.0)
    onehot = (col == lbl_ref[...].reshape(-1, 1)).astype(jnp.float32)
    dz = g_ref[...].reshape(-1, 1) * (
        p - (1.0 - eps) * onehot
        - jnp.where(valid, eps / n_valid_v, 0.0))
    rows_ok = _row_valid(tb, block_t, n_valid_t, dz.shape)
    return jnp.where(rows_ok, dz, 0.0)


def _bwd_dh_kernel(h_ref, w_ref, lbl_ref, lse_ref, g_ref, dh_ref,
                   dh_scr, *, block_t, block_v, n_valid_t, n_valid_v,
                   eps):
    from jax.experimental import pallas as pl

    tb = pl.program_id(0)
    vb = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vb == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    dz = _dz_block(h_ref, w_ref, lbl_ref, lse_ref, g_ref, tb, vb,
                   block_t=block_t, block_v=block_v,
                   n_valid_t=n_valid_t, n_valid_v=n_valid_v, eps=eps)
    # the vocab tail block's padded W columns are undefined memory; dz
    # is zero there but 0 * NaN poisons the contraction — zero them
    w = w_ref[...]
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32,
                                                  (1, w.shape[1]), 1)
    w = jnp.where(col < n_valid_v, w, 0)
    dh_scr[:] += jax.lax.dot_general(
        dz.astype(w.dtype), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vb == nv - 1)
    def _fin():
        dh_ref[...] = dh_scr[:].astype(dh_ref.dtype)


def _bwd_dw_kernel(h_ref, w_ref, lbl_ref, lse_ref, g_ref, dw_ref,
                   dw_scr, *, block_t, block_v, n_valid_t, n_valid_v,
                   eps):
    from jax.experimental import pallas as pl

    vb = pl.program_id(0)
    tb = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(tb == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    dz = _dz_block(h_ref, w_ref, lbl_ref, lse_ref, g_ref, tb, vb,
                   block_t=block_t, block_v=block_v,
                   n_valid_t=n_valid_t, n_valid_v=n_valid_v, eps=eps)
    # padded token rows of h are undefined memory; dz is zero there so
    # zero the h rows too before the contraction (0 * NaN poisons)
    h = h_ref[...]
    rows_ok = _row_valid(tb, block_t, n_valid_t, (h.shape[0], 1))
    h = jnp.where(rows_ok, h, 0)
    dw_scr[:] += jax.lax.dot_general(
        h, dz.astype(h.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(tb == nt - 1)
    def _fin():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)


def _fwd(h, w, labels, block_t, block_v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = h.shape
    v = w.shape[1]
    block_t = min(block_t, n)
    block_v = min(block_v, v)
    grid = (pl.cdiv(n, block_t), pl.cdiv(v, block_v))
    lse, zt, zsum = _pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v, n_valid_v=v),
        name="vocab_ce_fwd",
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, d), lambda t, vb: (t, 0)),
            pl.BlockSpec((d, block_v), lambda t, vb: (0, vb)),
            pl.BlockSpec((1, block_t), lambda t, vb: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((8, block_t), lambda t, vb: (0, t)),
            pl.BlockSpec((8, block_t), lambda t, vb: (0, t)),
            pl.BlockSpec((8, block_t), lambda t, vb: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, n), jnp.float32),
            jax.ShapeDtypeStruct((8, n), jnp.float32),
            jax.ShapeDtypeStruct((8, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_t, 1), jnp.float32)] * 4,
    )(h, w, labels.astype(jnp.int32).reshape(1, -1))
    return lse[0], zt[0], zsum[0]


def _bwd(h, w, labels, lse, g, eps, block_t, block_v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = h.shape
    v = w.shape[1]
    block_t = min(block_t, n)
    block_v = min(block_v, v)
    lbl = labels.astype(jnp.int32).reshape(1, -1)
    lse2 = lse.reshape(1, -1)
    g2 = g.astype(jnp.float32).reshape(1, -1)
    common = dict(block_t=block_t, block_v=block_v, n_valid_t=n,
                  n_valid_v=v, eps=eps)
    dh = _pallas_call(
        functools.partial(_bwd_dh_kernel, **common),
        name="vocab_ce_dh",
        grid=(pl.cdiv(n, block_t), pl.cdiv(v, block_v)),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda t, vb: (t, 0)),
            pl.BlockSpec((d, block_v), lambda t, vb: (0, vb)),
            pl.BlockSpec((1, block_t), lambda t, vb: (0, t)),
            pl.BlockSpec((1, block_t), lambda t, vb: (0, t)),
            pl.BlockSpec((1, block_t), lambda t, vb: (0, t)),
        ],
        out_specs=pl.BlockSpec((block_t, d), lambda t, vb: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
    )(h, w, lbl, lse2, g2)
    dw = _pallas_call(
        functools.partial(_bwd_dw_kernel, **common),
        name="vocab_ce_dw",
        grid=(pl.cdiv(v, block_v), pl.cdiv(n, block_t)),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda vb, t: (t, 0)),
            pl.BlockSpec((d, block_v), lambda vb, t: (0, vb)),
            pl.BlockSpec((1, block_t), lambda vb, t: (0, t)),
            pl.BlockSpec((1, block_t), lambda vb, t: (0, t)),
            pl.BlockSpec((1, block_t), lambda vb, t: (0, t)),
        ],
        out_specs=pl.BlockSpec((d, block_v), lambda vb, t: (0, vb)),
        out_shape=jax.ShapeDtypeStruct((d, v), w.dtype),
        scratch_shapes=[pltpu.VMEM((d, block_v), jnp.float32)],
    )(h, w, lbl, lse2, g2)
    return dh, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ce(h, w, labels, eps, block_t, block_v):
    lse, zt, zsum = _fwd(h, w, labels, block_t, block_v)
    v = w.shape[1]
    return lse - (1.0 - eps) * zt - (eps / v) * zsum


def _vjp_fwd(h, w, labels, eps, block_t, block_v):
    lse, zt, zsum = _fwd(h, w, labels, block_t, block_v)
    v = w.shape[1]
    loss = lse - (1.0 - eps) * zt - (eps / v) * zsum
    return loss, (h, w, labels, lse)


def _vjp_bwd(eps, block_t, block_v, res, g):
    h, w, labels, lse = res
    dh, dw = _bwd(h, w, labels, lse, g, eps, block_t, block_v)
    return dh, dw, None


_fused_ce.defvjp(_vjp_fwd, _vjp_bwd)


def fused_vocab_ce(hidden, weight, labels, epsilon=0.0,
                   block_t=DEFAULT_BLOCK_T, block_v=DEFAULT_BLOCK_V):
    """Per-token label-smoothed CE of `hidden @ weight` logits without
    materializing them.

    hidden: (..., D) activations (flattened internally); weight (D, V);
    labels (...) int token ids aligned with hidden's leading dims.
    Returns per-token loss with hidden's leading shape.  Differentiable
    w.r.t. hidden and weight (labels get no gradient)."""
    lead = hidden.shape[:-1]
    d = hidden.shape[-1]
    h2 = hidden.reshape(-1, d)
    lbl = labels.reshape(-1)
    if lbl.shape[0] != h2.shape[0]:
        raise ValueError(
            f"fused_vocab_ce: {h2.shape[0]} tokens but "
            f"{lbl.shape[0]} labels")
    # out-of-range labels clamp into [0, V) exactly like the non-fused
    # path's take_along_axis (mode='clip'); without this an invalid id
    # would leave the running target-logit at NEG and surface as a ~1e30
    # loss only on the fused path — a data bug must not look like a
    # backend bug
    lbl = jnp.clip(lbl, 0, weight.shape[1] - 1)
    loss = _fused_ce(h2, weight, lbl, float(epsilon), int(block_t),
                     int(block_v))
    return loss.reshape(lead)
