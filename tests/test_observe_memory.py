"""Memory observability tests (observe pillar 5, docs/OBSERVE.md).

The ISSUE 6 contracts, pinned on the CPU backend:
- buffer→fluid-op attribution: parameter allocations carry their state
  var NAMES (entry-parameter-number → pytree leaf join), temp buffers
  carry the fluid op scope the cost tables already use;
- bucket classification: params vs optimizer_state vs gradients vs
  activations vs workspace, with donated bytes tallied;
- the timeline's live-bytes curve is consistent with the table (its
  peak never exceeds the allocation total, never undercuts the
  resident floor) and exports as chrome-trace JSON;
- the fit planner's probe-extrapolated peak lands within
  PLAN_FIT_REL_TOL of the real buffer-assignment measurement on the
  ResNet-50 and Transformer test configs (the acceptance criterion);
- ServingEngine.start() rejects an impossible bucket ladder with a
  structured BucketMemoryError BEFORE compiling the ladder.

CPU `memory_analysis` numbers bound the program's buffer structure but
do not equal v5e HBM (layout/padding and fusion differ per backend) —
these tests pin the MACHINERY on one backend; absolute chip budgets
are a bench/ops concern.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observe
from paddle_tpu.observe.memory import (BUCKETS, PLAN_FIT_REL_TOL,
                                       compiled_peak_bytes)


def _mlp_train_program():
    """fc-relu-fc regression + Adam: small, but exercises every bucket
    (params, two Adam moments per param, AD backward, feeds).  Built
    under unique_name.guard() so the fc_0/fc_1 names the attribution
    tests assert on don't drift with suite ordering (CLAUDE.md)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[16], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


@pytest.fixture(scope="module")
def mlp_report():
    """(report, program, exe, scope, feed) for the shared small MLP."""
    main, startup, loss = _mlp_train_program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
    feed = {"x": np.zeros((8, 16), np.float32),
            "y": np.zeros((8, 1), np.float32)}
    rep = observe.memory_report(main, feed=feed, fetch_list=[loss],
                                scope=scope)
    return rep, main, loss, scope, feed


def test_buffer_attribution_names_state_vars(mlp_report):
    rep, *_ = mlp_report
    # this jax exposes the buffer assignment on CPU; if that ever
    # regresses the fallback is an ESTIMATE and these name joins are
    # the first thing to re-verify
    assert rep["source"] == "buffer_assignment"
    by_param = {r["param"]: r for r in rep["rows"] if r["param"]}
    # weights attribute BY NAME through the entry-parameter join
    assert "fc_0.w_0" in by_param and "fc_1.w_0" in by_param
    assert by_param["fc_0.w_0"]["bucket"] == "params"
    # 32x16 f32 weight = 2048 bytes exactly (CPU: no padding)
    assert by_param["fc_0.w_0"]["bytes"] == 16 * 32 * 4
    # Adam accumulators classify as optimizer state, not params
    assert by_param["fc_0.w_0.moment1"]["bucket"] == "optimizer_state"
    assert by_param["fc_0.w_0.moment2"]["bucket"] == "optimizer_state"
    # feeds are per-step activations, not resident state
    assert by_param["x"]["bucket"] == "activations"


def test_buffer_attribution_joins_fluid_ops(mlp_report):
    rep, *_ = mlp_report
    op_types = {r["op_type"] for r in rep["rows"] if r["op_type"]}
    # temp buffers carry the same fluid-op scopes the cost table joins
    assert "mul" in op_types, op_types  # the fc matmuls
    # and the AD backward lands in the gradients bucket
    grad_rows = [r for r in rep["rows"] if r["bucket"] == "gradients"]
    assert grad_rows and all(r["opcode"] != "parameter"
                             for r in grad_rows)


def test_bucket_breakdown_accounting(mlp_report):
    rep, *_ = mlp_report
    br = rep["breakdown"]
    assert set(BUCKETS) <= set(br) and "donated" in br
    # exact resident sizes: 2 weights + 2 biases
    params_exact = (16 * 32 + 32 + 32 * 1 + 1) * 4
    assert br["params"] >= params_exact
    # Adam: 2 moments per param (+ scalar beta pows / lr) — optimizer
    # state must be about twice the param bytes, never zero
    assert br["optimizer_state"] >= 2 * params_exact
    assert br["gradients"] > 0 and br["activations"] > 0
    # donated params share their allocation with the updated value: the
    # training step donates state, so donated covers at least params
    assert br["donated"] >= params_exact
    assert rep["peak_bytes"] > 0
    # XLA's own CompiledMemoryStats arithmetic must agree with the
    # allocation total (both describe the same assignment)
    if "stats" in rep:
        s = rep["stats"]
        xla_total = (s["argument_bytes"] + s["output_bytes"]
                     + s["temp_bytes"] - s["alias_bytes"])
        assert abs(xla_total - rep["peak_bytes"]) \
            <= 0.001 * rep["peak_bytes"] + 1024


def test_memory_table_sorted_and_formatted(mlp_report):
    rep, main, loss, scope, feed = mlp_report
    rows = observe.memory_table(main, feed=feed, fetch_list=[loss],
                                scope=scope, top=5)
    assert len(rows) == 5
    assert [r["bytes"] for r in rows] == sorted(
        (r["bytes"] for r in rows), reverse=True)
    text = observe.format_memory_table(rep["rows"], top=8)
    assert "fc_0.w_0" in text and "Bucket" in text
    assert "more buffers" in text  # truncation line


def test_timeline_consistent_with_table(mlp_report):
    rep, main, loss, scope, feed = mlp_report
    tl = observe.memory_timeline(main, feed=feed, fetch_list=[loss],
                                 scope=scope)
    assert tl["source"] == rep["source"]
    assert 0 < tl["peak_live_bytes"] <= rep["peak_bytes"]
    # the curve floor is the resident set (params/constants/outputs);
    # every point sits on or above it, and the recorded peak IS the
    # curve's max at the recorded index
    lives = [live for _idx, live in tl["points"]]
    assert all(v >= tl["resident_bytes"] for v in lives)
    assert max(lives) == tl["peak_live_bytes"]
    peak_point = [live for idx, live in tl["points"]
                  if idx == tl["peak_index"]]
    assert peak_point and max(peak_point) == tl["peak_live_bytes"]
    # indices follow the instruction schedule (sorted, in range)
    idxs = [idx for idx, _ in tl["points"]]
    assert idxs == sorted(idxs)
    assert 0 <= tl["peak_index"] < tl["n_instructions"]
    assert tl["live_at_peak"], "nothing alive at the peak?"
    assert all(s["lo"] <= tl["peak_index"] <= s["hi"]
               for s in tl["live_at_peak"])


def test_chrome_trace_export(mlp_report, tmp_path):
    rep, main, loss, scope, feed = mlp_report
    tl = observe.memory_timeline(main, feed=feed, fetch_list=[loss],
                                 scope=scope)
    path = observe.export_chrome_trace(tl, str(tmp_path / "mem.json"))
    with open(path) as f:
        trace = json.load(f)
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert len(counters) == len(tl["points"])
    peaks = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert len(peaks) == 1
    assert peaks[0]["args"]["peak_live_bytes"] == tl["peak_live_bytes"]


def test_step_mem_breakdown_shape(mlp_report):
    rep, main, loss, scope, feed = mlp_report
    mb = observe.step_mem_breakdown(main, feed=feed, fetch_list=[loss],
                                    scope=scope)
    assert mb["peak_bytes"] == rep["peak_bytes"]
    assert mb["source"] == rep["source"]
    assert set(BUCKETS) <= set(mb)


def test_program_costs_carries_peak_hbm(mlp_report):
    rep, main, loss, scope, feed = mlp_report
    from paddle_tpu.observe.cost import program_costs

    out = program_costs(main, feed=feed, fetch_list=[loss], scope=scope)
    assert out["peak_hbm_bytes"] == rep["peak_bytes"]


# -- the fit planner ----------------------------------------------------

def _plan_vs_measured(program, loss, scope, cand_feed, batch,
                      probe_batches):
    exe = fluid.Executor()
    plan = observe.plan_fit(program, cand_feed, fetch_list=[loss],
                            scope=scope, exe=exe,
                            probe_batches=probe_batches)
    assert plan["exact"] is False  # extrapolated, not measured
    measured_feed = {n: np.zeros(tuple(v.shape), v.dtype)
                     for n, v in cand_feed.items()}
    compiled = exe.compiled_step(program, feed=measured_feed,
                                 fetch_list=[loss], scope=scope)
    actual = compiled_peak_bytes(compiled)
    assert actual and actual > 0
    rel = abs(plan["predicted_peak_bytes"] - actual) / actual
    return plan, actual, rel


def test_plan_fit_accuracy_resnet50():
    """Acceptance: plan_fit within PLAN_FIT_REL_TOL (10%) of the real
    measurement for the ResNet-50 test config, probes never touching
    the candidate batch."""
    import jax

    from paddle_tpu.models import resnet

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = resnet.build_model(dataset="flowers", depth=50,
                                   class_dim=1000, learning_rate=0.1)
        exe = fluid.Executor()
        exe.run(startup)
    cand = {"data": jax.ShapeDtypeStruct((8, 3, 224, 224), "float32"),
            "label": jax.ShapeDtypeStruct((8, 1), "int32")}
    plan, actual, rel = _plan_vs_measured(main, model["loss"], scope,
                                          cand, 8, (1, 2))
    assert rel <= PLAN_FIT_REL_TOL, \
        (plan["predicted_peak_bytes"], actual, rel)
    assert plan["breakdown"]["params"] > 0
    assert plan["breakdown"]["optimizer_state"] > 0


def test_plan_fit_accuracy_transformer():
    import jax

    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = transformer.build_model(
            src_vocab_size=1000, trg_vocab_size=1000, max_length=32,
            n_layer=2, n_head=4, d_model=64, d_inner_hid=128,
            dropout=0.1)
        exe = fluid.Executor()
        exe.run(startup)
    batch = transformer.make_fake_batch(16, 32, 1000, 1000)
    cand = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for n, v in batch.items()}
    plan, actual, rel = _plan_vs_measured(main, model["loss"], scope,
                                          cand, 16, (2, 4))
    assert rel <= PLAN_FIT_REL_TOL, \
        (plan["predicted_peak_bytes"], actual, rel)
    # 16 = 4x the largest probe: a real extrapolation
    assert plan["probe_batches"] == [2, 4]
    assert plan["batch"] == 16


def test_plan_fit_probe_sized_candidate_is_exact():
    main, startup, loss = _mlp_train_program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
    import jax

    cand = {"x": jax.ShapeDtypeStruct((2, 16), "float32"),
            "y": jax.ShapeDtypeStruct((2, 1), "float32")}
    plan = observe.plan_fit(main, cand, fetch_list=[loss], scope=scope,
                            probe_batches=(2, 4))
    assert plan["exact"] is True and plan["probe_batches"] == [2]


def test_plan_fit_rejects_uninferrable_batch():
    main, startup, loss = _mlp_train_program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
    with pytest.raises(ValueError, match="feed"):
        observe.plan_fit(main, {}, fetch_list=[loss], scope=scope)


# -- serving ladder validation ------------------------------------------

@pytest.fixture(scope="module")
def serving_model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("mem_serving"))
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[16], append_batch_size=True)
        pred = layers.fc(layers.fc(x, size=32, act="relu"), size=4)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


def test_serving_rejects_impossible_bucket(serving_model_dir):
    from paddle_tpu.observe import runtime_stats
    from paddle_tpu.serving import (BucketConfig, BucketMemoryError,
                                    ServingEngine)

    snap = runtime_stats.snapshot()
    engine = ServingEngine(serving_model_dir,
                           {"x": np.zeros(16, np.float32)},
                           buckets=BucketConfig((1, 2, 4, 8)),
                           memory_budget_bytes=4096)
    with pytest.raises(BucketMemoryError) as ei:
        engine.start()
    d = ei.value.as_dict()
    assert d["error"] == "bucket_memory"
    assert d["budget_bytes"] == 4096
    # the largest bucket is the offender; every offending row carries
    # its predicted bytes
    assert any(b["batch_size"] == 8 for b in d["offending_buckets"])
    assert all(b["predicted_peak_bytes"] > 4096
               for b in d["offending_buckets"])
    # the ladder (4 buckets) was NOT compiled: only the 2 probes were
    assert runtime_stats.delta(snap)["compiles"] <= 2


def test_serving_fit_plan_recorded_when_budget_fits(serving_model_dir):
    from paddle_tpu.serving import BucketConfig, ServingEngine

    engine = ServingEngine(serving_model_dir,
                           {"x": np.zeros(16, np.float32)},
                           buckets=BucketConfig((1, 2)),
                           memory_budget_bytes=10**9)
    engine.start()
    try:
        plan = engine.fit_plan
        assert plan["budget_bytes"] == 10**9
        assert len(plan["buckets"]) == 2
        assert all(b["fits"] for b in plan["buckets"])
        # probe-sized buckets are measured exactly, not extrapolated
        assert all(b["exact"] for b in plan["buckets"])
        out = engine.infer({"x": np.zeros(16, np.float32)},
                           timeout_s=60)
        assert out[0].shape == (4,)
    finally:
        engine.close()


def test_serving_no_budget_skips_validation(serving_model_dir):
    from paddle_tpu.serving import BucketConfig, ServingEngine

    # CPU default: no device budget known -> validation skipped, tagged
    engine = ServingEngine(serving_model_dir,
                           {"x": np.zeros(16, np.float32)},
                           buckets=BucketConfig((1,)))
    engine.start()
    try:
        assert engine.fit_plan == {"skipped": "no device budget known",
                                   "budget_bytes": None}
    finally:
        engine.close()


def test_serving_budget_false_disables(serving_model_dir):
    from paddle_tpu.serving import BucketConfig, ServingEngine

    engine = ServingEngine(serving_model_dir,
                           {"x": np.zeros(16, np.float32)},
                           buckets=BucketConfig((1,)),
                           memory_budget_bytes=False)
    engine.start()
    try:
        assert engine.fit_plan is None
    finally:
        engine.close()
