"""Python-side metric accumulators.

TPU-native analog of the reference's metrics module
(reference: python/paddle/fluid/metrics.py:1 — MetricBase, CompositeMetric,
Precision, Recall, Accuracy, ChunkEvaluator, EditDistance, DetectionMAP,
Auc).  These compose *across batches* on the host: per-batch statistics
come out of fetched ops (accuracy/auc/precision_recall ops or raw
predictions) and accumulate in numpy; nothing here runs on device.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Sequence

import numpy as np


def _to_np(x):
    return np.asarray(x)


class MetricBase:
    """reference metrics.py MetricBase: name + reset/update/eval."""

    def __init__(self, name: Optional[str] = None):
        self._name = name or self.__class__.__name__

    def reset(self):
        """Zero every accumulator attribute (reference resets all
        non-underscore state)."""
        states = {k: v for k, v in self.__dict__.items()
                  if not k.startswith("_")}
        for k, v in states.items():
            if isinstance(v, int):
                setattr(self, k, 0)
            elif isinstance(v, float):
                setattr(self, k, 0.0)
            elif isinstance(v, (np.ndarray,)):
                setattr(self, k, np.zeros_like(v))
            elif isinstance(v, (list,)):
                setattr(self, k, [])

    def get_config(self):
        return {k: copy.deepcopy(v) for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    @property
    def name(self):
        return self._name


class CompositeMetric(MetricBase):
    """Bundle several metrics updated with the same inputs
    (reference metrics.py CompositeMetric)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics: List[MetricBase] = []

    def add_metric(self, metric: MetricBase):
        if not isinstance(metric, MetricBase):
            raise TypeError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds=preds, labels=labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over thresholded predictions
    (reference metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels)
        rounded = (preds.reshape(-1) >= 0.5).astype(np.int64)
        flat = labels.reshape(-1)
        self.tp += int(np.sum((rounded == 1) & (flat == 1)))
        self.fp += int(np.sum((rounded == 1) & (flat == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    """reference metrics.py Recall."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels)
        rounded = (preds.reshape(-1) >= 0.5).astype(np.int64)
        flat = labels.reshape(-1)
        self.tp += int(np.sum((rounded == 1) & (flat == 1)))
        self.fn += int(np.sum((rounded == 0) & (flat == 1)))

    def eval(self):
        rel = self.tp + self.fn
        return float(self.tp) / rel if rel else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracies (reference
    metrics.py Accuracy — pairs with the accuracy op's batch value)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        if weight < 0:
            raise ValueError("weight must be non-negative")
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunk-level precision/recall/F1 accumulation (reference
    metrics.py ChunkEvaluator; batch counts typically from a chunk_eval
    computation or host-side chunk extraction)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Average edit distance + instance error rate (reference
    metrics.py EditDistance; pairs with the edit_distance op's (Out,
    SequenceNum) fetches)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = _to_np(distances).reshape(-1)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(np.asarray(seq_num).reshape(-1)[0])
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no batches accumulated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """Threshold-bucketed ROC AUC accumulator (reference metrics.py Auc:
    _stat_pos/_stat_neg histograms + trapezoid integration), composable
    across batches."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        if curve != "ROC":
            raise ValueError("only ROC supported")
        self._num_thresholds = num_thresholds
        self.stat_pos = np.zeros(num_thresholds + 1, np.int64)
        self.stat_neg = np.zeros(num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        """preds: (N, 2) class probabilities (or (N,) positive scores);
        labels: (N,) / (N,1) binary."""
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        pos_score = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip((pos_score * self._num_thresholds).astype(np.int64),
                      0, self._num_thresholds)
        np.add.at(self.stat_pos, idx[labels == 1], 1)
        np.add.at(self.stat_neg, idx[labels == 0], 1)

    def eval(self):
        # sweep thresholds from high to low, trapezoid over (fp, tp)
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            prev_pos, prev_neg = tot_pos, tot_neg
            tot_pos += float(self.stat_pos[i])
            tot_neg += float(self.stat_neg[i])
            auc += (tot_neg - prev_neg) * (tot_pos + prev_pos) / 2.0
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0


class DetectionMAP(MetricBase):
    """Running mean of per-batch mAP values (reference metrics.py
    DetectionMAP — accumulates the detection_map computation's output)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight
