"""Metric layers.

reference: python/paddle/fluid/layers/metric_op.py — accuracy, auc
(ops in paddle/fluid/operators/metrics/).
"""

from __future__ import annotations

from ..initializer import Constant
from ..layer_helper import LayerHelper
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference metric_op.py accuracy): top_k + accuracy
    ops."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int64")
    if total is None:
        total = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC with persistable histogram state
    (reference metric_op.py auc)."""
    helper = LayerHelper("auc")
    stat_pos = helper.create_or_get_global_variable(
        f"{helper.name}.stat_pos", [num_thresholds + 1], "float32",
        initializer=Constant(0.0))
    stat_neg = helper.create_or_get_global_variable(
        f"{helper.name}.stat_neg", [num_thresholds + 1], "float32",
        initializer=Constant(0.0))
    auc_out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"num_thresholds": num_thresholds, "curve": curve})
    return auc_out, [stat_pos, stat_neg]
