"""Ragged paged-attention decode kernel (Pallas, TPU).

The serving-decode analog of flash_attention.py: one query token per
batch slot attends over that slot's K/V PAGES — fixed-size blocks of a
shared pool, addressed through a per-slot page table — masked to the
slot's true length (Ragged Paged Attention, PAPERS.md arxiv 2604.15464;
the input contract is exactly the repo's ragged padded-dense +
lengths convention applied to a block pool instead of a dense buffer).

Layout contract (head-major end-to-end, ISSUE 8/12): the query arrives
(S, H*D) head-grouped — exactly what the attn_qkv projection emits —
and the pools are (P, page, H*D) in the same grouping, so a page write
is a row scatter and NOTHING transposes at the kernel boundary.

Grid: (S, max_pages) with the page axis innermost; the page table and
lengths ride as SCALAR-PREFETCH operands so each k/v BlockSpec index
map dereferences the page table directly — pallas double-buffers the
page DMAs, no manual copy loop.  Each k/v block is one FULL page row
(1, page, H*D): the whole grouped minor dim travels in one contiguous
DMA and the head split happens in-kernel as static lane slices (the
decode q is a single token, so scores are VPU reductions — a 1-row MXU
matmul would waste the systolic array anyway).  Pages at or beyond a
slot's length are predicated off, and the online-softmax running
(m, l, acc) state lives in VMEM scratch across the page axis, one lane
per head.

Optional int8 pools: k/v arrive int8 with per-token-row f32 scale
sidecars (P, page, 1) — the blockwise scheme of
parallel/collectives.py applied per cache row — dequantized in-kernel.

The query block is (1, 1, H*D): the wrapper reshapes q to (S, 1, H*D)
(free minor-dim split, not a transpose) so the sublane-1 memref is an
explicit array dim — the same <1xN>-layout hint jax's reference
paged-attention kernel uses — and the kernel runs in f32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Default logical page: 16 tokens.  Small pages waste less pool on
# ragged tails; the per-page DMA is (page, H*D) so even 16 rows is a
# full-lane contiguous transfer.  Lives HERE per the r05 rule: call
# sites must not carry stale fallbacks.
DEFAULT_PAGE_SIZE = 16


# -- kernel cost registry (observe/cost.py injects these at the custom
# -- call instructions; same dense-equivalent convention as flash) ------
#
# Dense-equivalent flops: every slot attends over its FULL page-table
# capacity T_cap = max_pages * page_size (that is what the XLA
# dense-gather twin computes once): qk^T + pv = 4 * S * T_cap * (H*d)
# per decode step.  The per-score softmax constant cannot be recovered
# from the operand shapes (H is folded into the grouped minor dim), so
# only the dot flops are credited — they dominate at any real d.
# Bytes: q/out once plus the S * max_pages pages the kernel actually
# gathers (NOT the whole pool — a mostly-empty pool is not traffic).

def _find_paged_dims(operand_shapes):
    """(s, hd, page, maxp, kv_elem_bytes) from the custom call's
    operands: page_table (S*maxp,) i32, lengths (S,) i32, q (S, 1, HD),
    then k/v pools (P, page, HD) [+ optional (P, page, 1) scales]."""
    q = next(dims for dims, _ in operand_shapes
             if len(dims) == 3 and dims[1] == 1)
    kv = next((dims, eb) for dims, eb in operand_shapes
              if len(dims) == 3 and dims[2] == q[2] and dims[1] != 1)
    one_d = sorted(dims[0] for dims, _ in operand_shapes
                   if len(dims) == 1)
    s = q[0]
    maxp = one_d[-1] // s if s else 0
    return s, q[2], kv[0][1], maxp, kv[1]


def paged_attn_cost(operand_shapes, result_shapes):
    s, hd, page, maxp, kv_eb = _find_paged_dims(operand_shapes)
    t_cap = maxp * page
    flops = 4.0 * s * t_cap * hd
    io = float(2 * s * hd * 4                  # q + out (f32)
               + 2 * s * t_cap * hd * kv_eb    # gathered k + v pages
               + s * 4 + s * maxp * 4)         # lengths + page table
    return flops, io


def _register_costs():
    from . import register_kernel_cost

    register_kernel_cost("paged_attn", paged_attn_cost)


_register_costs()


def _pallas_call(*args, **kw):
    from . import pallas_call  # shared interpret gate (package init)

    return pallas_call(*args, **kw)


def _paged_attn_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, m_scr, l_scr, acc_scr, *, scale,
                       page, maxp, n_head, d):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[s]

    @pl.when(p * page < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                   # (1, H*D)
        k = k_ref[0].astype(jnp.float32)                   # (page, H*D)
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0].astype(jnp.float32)          # (page, 1)
        if vs_ref is not None:
            v = v * vs_ref[0].astype(jnp.float32)
        pos = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (page, 1), 0)
        valid = pos < length
        # zero invalid v rows: 0 * undefined-pool-memory would poison
        v = jnp.where(valid, v, 0.0)
        # static head loop over lane slices of the grouped minor dim —
        # one token's scores per head are a VPU reduction, kept
        # (page, 1) so the per-slot scalars broadcast along sublanes
        for h in range(n_head):
            hs = slice(h * d, (h + 1) * d)
            s_col = jnp.sum(k[:, hs] * q[:, hs], axis=1,
                            keepdims=True) * scale         # (page, 1)
            s_col = jnp.where(valid, s_col, NEG_INF)
            m_prev = m_scr[:, h:h + 1]                     # (1, 1)
            m_cur = jnp.max(s_col, axis=0,
                            keepdims=True).reshape(1, 1)
            m_new = jnp.maximum(m_prev, m_cur)
            pw = jnp.exp(s_col - m_new)                    # (page, 1)
            alpha = jnp.exp(m_prev - m_new)                # (1, 1)
            acc_scr[:, hs] = acc_scr[:, hs] * alpha + jax.lax.dot_general(
                pw, v[:, hs], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # (1, d)
            l_scr[:, h:h + 1] = alpha * l_scr[:, h:h + 1] + jnp.sum(
                pw, axis=0, keepdims=True).reshape(1, 1)
            m_scr[:, h:h + 1] = m_new

    @pl.when(p == maxp - 1)
    def _finalize():
        o = jnp.concatenate(
            [acc_scr[:, h * d:(h + 1) * d]
             / jnp.maximum(l_scr[:, h:h + 1], 1e-30)
             for h in range(n_head)], axis=1)              # (1, H*D)
        o_ref[0] = o.astype(o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, page_table, lengths,
                           *, n_head, scale=None, k_scales=None,
                           v_scales=None):
    """Decode-step attention over paged KV.

    q: (S, H*D) head-grouped queries, one token per slot.
    k_pages/v_pages: (P, page, H*D) pools (f32/bf16, or int8 with the
        per-row scale sidecars).
    page_table: (S, max_pages) int32 — physical page of each logical
        page; entries past a slot's used range must still be valid
        indices (the host keeps them 0) — they are DMA'd and masked.
    lengths: (S,) int32 — valid tokens per slot (prompt + committed).
    k_scales/v_scales: optional (P, page, 1) f32 sidecars (int8 pools).

    Returns (S, H*D) in q's dtype."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_slots, hd = q.shape
    n_pages, page, hd_kv = k_pages.shape
    if hd_kv != hd:
        raise ValueError(f"q minor dim {hd} != pool minor dim {hd_kv}")
    if hd % n_head:
        raise ValueError(f"minor dim {hd} not divisible by n_head "
                         f"{n_head}")
    d = hd // n_head
    maxp = page_table.shape[1]
    if scale is None:
        scale = d ** -0.5
    has_scales = k_scales is not None

    # (S, 1, H*D): free minor split making the 1-sublane q memref an
    # explicit dim (the jax paged-attention <1xN> layout hint); the
    # kernel launches in f32
    q3 = q.reshape(s_slots, 1, hd).astype(jnp.float32)

    # index maps receive the grid indices first, then the scalar
    # prefetch refs (page table, lengths) as trailing arguments
    def q_idx(s, p, pt, ln):
        return (s, 0, 0)

    def kv_idx(s, p, pt, ln):
        return (pt[s * maxp + p], 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, hd), q_idx),
        pl.BlockSpec((1, page, hd), kv_idx),
        pl.BlockSpec((1, page, hd), kv_idx),
    ]
    args = [q3, k_pages, v_pages]
    if has_scales:
        in_specs += [pl.BlockSpec((1, page, 1), kv_idx),
                     pl.BlockSpec((1, page, 1), kv_idx)]
        args += [k_scales, v_scales]

    def kern(*refs):
        pt_r, ln_r = refs[0], refs[1]
        n_in = 3 + 2 * has_scales
        ins, rest = refs[2:2 + n_in], refs[2 + n_in:]
        q_r, k_r, v_r = ins[:3]
        ks_r, vs_r = (ins[3], ins[4]) if has_scales else (None, None)
        _paged_attn_kernel(pt_r, ln_r, q_r, k_r, v_r, ks_r, vs_r,
                           *rest, scale=float(scale), page=page,
                           maxp=maxp, n_head=n_head, d=d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, hd), q_idx),
        scratch_shapes=[
            pltpu.VMEM((1, n_head), jnp.float32),   # running max/head
            pltpu.VMEM((1, n_head), jnp.float32),   # running norm/head
            pltpu.VMEM((1, hd), jnp.float32),       # output accumulator
        ],
    )
    out = _pallas_call(
        kern,
        name="paged_attn",
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, 1, hd), jnp.float32),
    )(page_table.reshape(-1).astype(jnp.int32),
      lengths.astype(jnp.int32), *args)
    return out.reshape(s_slots, hd).astype(q.dtype)
