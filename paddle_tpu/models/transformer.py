"""Transformer NMT (encoder-decoder), the flagship benchmark model.

reference: benchmark/fluid's Transformer config (machine translation) and
the fluid Transformer implementation pattern (pre/post-process wrappers
around multi-head attention + FFN).  Attention is composed from
matmul/softmax layers — XLA fuses the chain onto the MXU; masks are
additive biases built in-graph from sequence lengths (segment-style
replacement for LoD, SURVEY.md §5.7).  A Pallas flash-attention kernel
(ops/pallas/flash_attention.py) can replace the composed attention via
use_flash=True.
"""

from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..param_attr import ParamAttr
from ..initializer import Normal


def multi_head_attention(queries, keys, values, attn_bias, d_key, d_value,
                         d_model, n_head, dropout_rate=0.0,
                         use_flash=False, fused_qkv=False,
                         flash_pallas=None, causal=False,
                         head_major=False):
    if head_major:
        # Head-major end-to-end (ISSUE 8): the attn_qkv projections'
        # (N, T, H*d) head-grouped outputs feed the flash op's
        # layout="nthd" contract DIRECTLY and its (N, T, H*d) output
        # feeds attn_out — the (N,T,H*d)<->(N,H,T,d) transpose
        # round-trip at every kernel boundary (the r05 longctx profile:
        # ~15.9 s copy/transpose vs ~5.0 s kernel) ceases to exist.
        # Layer names are IDENTICAL to the baseline path, so the
        # Megatron column/row ShardingRules and the one-allreduce-per-
        # block property are untouched (asserted in
        # tests/test_head_major.py).
        if keys is None and fused_qkv:
            group = 2 * d_key + d_value
            qkv = layers.fc(queries, size=group * n_head,
                            num_flatten_dims=2, bias_attr=False,
                            name="attn_qkv")
            # head-grouped minor dim: [q_h|k_h|v_h] per head h — view
            # as (N, T, H, group), slice the minor axis, merge back.
            # reshape/slice only; no transpose.
            r = layers.reshape(qkv, shape=[0, 0, n_head, group])
            q = layers.reshape(
                layers.slice(r, axes=[3], starts=[0], ends=[d_key]),
                shape=[0, 0, n_head * d_key])
            k = layers.reshape(
                layers.slice(r, axes=[3], starts=[d_key],
                             ends=[2 * d_key]),
                shape=[0, 0, n_head * d_key])
            v = layers.reshape(
                layers.slice(r, axes=[3], starts=[2 * d_key],
                             ends=[group]),
                shape=[0, 0, n_head * d_value])
        else:
            if keys is None:  # self-attention
                keys, values = queries, queries
            q = layers.fc(queries, size=d_key * n_head,
                          num_flatten_dims=2, bias_attr=False,
                          name="attn_qkv")
            k = layers.fc(keys, size=d_key * n_head, num_flatten_dims=2,
                          bias_attr=False, name="attn_qkv")
            v = layers.fc(values, size=d_value * n_head,
                          num_flatten_dims=2, bias_attr=False,
                          name="attn_qkv")
        # NOTE: like the flash path below, head-major attention has no
        # dropout on the attention weights (the flash op's contract)
        ctx = layers.flash_attention(q, k, v, attn_bias,
                                     scale=d_key ** -0.5,
                                     causal=causal,
                                     use_pallas=flash_pallas,
                                     layout="nthd", n_head=n_head)
        return layers.fc(ctx, size=d_model, num_flatten_dims=2,
                         bias_attr=False, name="attn_out")
    if keys is None and fused_qkv:
        # Megatron-style fused QKV: ONE (D, (2dk+dv)·H) matmul instead
        # of three — a 3× wider MXU tile per layer.  The fused output
        # dim is HEAD-GROUPED ([q_h|k_h|v_h] per head h), so an mp
        # split of the fused dim lands on whole heads whenever mp
        # divides n_head — exactly the unfused column-parallel layout.
        # The reshape below then maps the mp shards onto the H axis and
        # the per-head q/k/v slices are shard-local: one allreduce per
        # attention block is preserved at any mp | n_head.  The layer
        # name keeps the attn_qkv prefix so the column-parallel rule
        # applies unchanged.
        group = 2 * d_key + d_value
        qkv = layers.fc(queries, size=group * n_head,
                        num_flatten_dims=2, bias_attr=False,
                        name="attn_qkv")
        r = layers.reshape(qkv, shape=[0, 0, n_head, group])
        r = layers.transpose(r, perm=[0, 2, 1, 3])  # (N, H, T, group)
        q = layers.slice(r, axes=[3], starts=[0], ends=[d_key])
        k = layers.slice(r, axes=[3], starts=[d_key], ends=[2 * d_key])
        v = layers.slice(r, axes=[3], starts=[2 * d_key],
                         ends=[group])
    else:
        if keys is None:  # self-attention
            keys, values = queries, queries
        # layer names drive the Megatron row/col sharding rules
        # (parallel/strategies.py): attn_qkv_* weights shard
        # column-parallel (output heads over mp), attn_out_*
        # row-parallel (input heads over mp) — one all-reduce per
        # attention block instead of three.
        q = layers.fc(queries, size=d_key * n_head, num_flatten_dims=2,
                      bias_attr=False, name="attn_qkv")
        k = layers.fc(keys, size=d_key * n_head, num_flatten_dims=2,
                      bias_attr=False, name="attn_qkv")
        v = layers.fc(values, size=d_value * n_head, num_flatten_dims=2,
                      bias_attr=False, name="attn_qkv")

        def split_heads(x, d):
            # (N, T, H*d) -> (N, H, T, d)
            rr = layers.reshape(x, shape=[0, 0, n_head, d])
            return layers.transpose(rr, perm=[0, 2, 1, 3])

        q = split_heads(q, d_key)
        k = split_heads(k, d_key)
        v = split_heads(v, d_value)

    if use_flash:
        # flash_pallas=True routes through the tiled Pallas kernel
        # (ops/pallas/flash_attention.py); default None/False keeps the
        # XLA composition inside the op — the historically-benched path.
        # causal=True (decoder self-attn under flash) uses the op's
        # in-kernel causal masking with a key-padding-only bias, the
        # form the Pallas kernel supports natively.
        ctx = layers.flash_attention(q, k, v, attn_bias,
                                     scale=d_key ** -0.5,
                                     causal=causal,
                                     use_pallas=flash_pallas)
    else:
        product = layers.matmul(q, k, transpose_y=True,
                                alpha=d_key ** -0.5)
        if attn_bias is not None:
            product = layers.elementwise_add(product, attn_bias)
        weights = layers.softmax(product)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                     dropout_implementation="upscale_in_train")
        ctx = layers.matmul(weights, v)

    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = layers.reshape(ctx, shape=[0, 0, n_head * d_value])
    return layers.fc(ctx, size=d_model, num_flatten_dims=2,
                     bias_attr=False, name="attn_out")


def positionwise_feed_forward(x, d_inner, d_model, act="relu"):
    # ffn_in column-parallel, ffn_out row-parallel (classic Megatron MLP)
    hidden = layers.fc(x, size=d_inner, num_flatten_dims=2, act=act,
                       name="ffn_in")
    return layers.fc(hidden, size=d_model, num_flatten_dims=2,
                     name="ffn_out")


def pre_post_process(prev_out, out, process_cmd, dropout_rate=0.0):
    """'a' residual-add, 'n' layer-norm, 'd' dropout (reference
    pre_process_layer/post_process_layer convention)."""
    for cmd in process_cmd:
        if cmd == "a":
            out = layers.elementwise_add(out, prev_out) \
                if prev_out is not None else out
        elif cmd == "n":
            out = layers.layer_norm(out, begin_norm_axis=len(out.shape) - 1)
        elif cmd == "d":
            if dropout_rate:
                out = layers.dropout(
                    out, dropout_prob=dropout_rate,
                    dropout_implementation="upscale_in_train")
    return out


def _ffn_or_moe(x, d_inner, d_model, moe_experts, aux_list):
    """FFN sublayer: dense (default) or a switch-MoE block with the
    expert dim sharded over mp/ep (moe_experts > 0).  Aux load-balance
    losses accumulate into aux_list for the objective."""
    if not moe_experts:
        return positionwise_feed_forward(x, d_inner, d_model)
    out, aux, _frac = layers.switch_moe(x, num_experts=moe_experts,
                                        d_inner=d_inner)
    if aux_list is not None:
        aux_list.append(aux)
    return out


def encoder_layer(x, attn_bias, n_head, d_key, d_value, d_model, d_inner,
                  dropout, use_flash=False, fused_qkv=False,
                  moe_experts=0, aux_list=None, flash_pallas=None,
                  head_major=False):
    attn = multi_head_attention(
        pre_post_process(None, x, "n"), None, None, attn_bias, d_key,
        d_value, d_model, n_head, dropout, use_flash=use_flash,
        fused_qkv=fused_qkv, flash_pallas=flash_pallas,
        head_major=head_major)
    attn = pre_post_process(x, attn, "ad", dropout)
    ff = _ffn_or_moe(pre_post_process(None, attn, "n"), d_inner,
                     d_model, moe_experts, aux_list)
    return pre_post_process(attn, ff, "ad", dropout)


def decoder_layer(x, enc_out, self_bias, cross_bias, n_head, d_key, d_value,
                  d_model, d_inner, dropout, use_flash=False,
                  fused_qkv=False, moe_experts=0, aux_list=None,
                  flash_pallas=None, self_causal=False,
                  flash_cross=False, head_major=False):
    self_attn = multi_head_attention(
        pre_post_process(None, x, "n"), None, None, self_bias, d_key,
        d_value, d_model, n_head, dropout, use_flash=use_flash,
        fused_qkv=fused_qkv, flash_pallas=flash_pallas,
        causal=self_causal, head_major=head_major)
    self_attn = pre_post_process(x, self_attn, "ad", dropout)
    q = pre_post_process(None, self_attn, "n")
    # flash_cross routes CROSS attention through the flash op too
    # (key-padding bias, non-causal) — required at long sequence
    # lengths where the composed path would materialize the
    # (N, H, T, T) weight tensor; default off to keep the historically
    # benched short-sequence program unchanged.  head_major forces it:
    # a composed cross-attention would reintroduce the boundary
    # transposes the head-major layout exists to delete.
    cross = multi_head_attention(q, enc_out, enc_out, cross_bias, d_key,
                                 d_value, d_model, n_head, dropout,
                                 use_flash=flash_cross or head_major,
                                 flash_pallas=(flash_pallas
                                               if flash_cross else None),
                                 head_major=head_major)
    cross = pre_post_process(self_attn, cross, "ad", dropout)
    ff = _ffn_or_moe(pre_post_process(None, cross, "n"), d_inner,
                     d_model, moe_experts, aux_list)
    return pre_post_process(cross, ff, "ad", dropout)


def _fold_moe_aux(avg_cost, moe_aux, weight):
    """objective += weight * sum of per-layer load-balance losses."""
    if not moe_aux:
        return avg_cost
    total = moe_aux[0] if len(moe_aux) == 1 else layers.sums(moe_aux)
    return layers.elementwise_add(
        avg_cost, layers.scale(layers.reduce_sum(total),
                               scale=float(weight)))


def _word_embedding(ids, vocab_size, d_model, name):
    emb = layers.embedding(
        ids, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=name,
                             initializer=Normal(0.0, d_model ** -0.5)))
    return layers.scale(emb, scale=d_model ** 0.5)


def _prepare_input(ids, vocab_size, d_model, max_len, dropout, name):
    emb = _word_embedding(ids, vocab_size, d_model, name)
    emb = layers.add_position_encoding(emb)
    if dropout:
        emb = layers.dropout(emb, dropout_prob=dropout,
                             dropout_implementation="upscale_in_train")
    return emb


def _padding_bias(seq_len, max_len):
    """(N,) lengths → additive attention bias (N, 1, 1, T): 0 valid,
    -1e9 padded."""
    m = layers.sequence_mask(seq_len, maxlen=max_len, dtype="float32")
    bias = layers.scale(m, scale=1e9, bias=-1e9)
    return layers.unsqueeze(layers.unsqueeze(bias, axes=[1]), axes=[1])


def _causal_bias(max_len):
    """(1, 1, T, T) additive bias: 0 where col <= row else -1e9."""
    r = layers.range(0, max_len, 1, "float32")
    row = layers.reshape(r, shape=[max_len, 1])
    col = layers.reshape(r, shape=[1, max_len])
    allowed = layers.cast(layers.less_equal(col, row), "float32")
    bias = layers.scale(allowed, scale=1e9, bias=-1e9)
    return layers.unsqueeze(layers.unsqueeze(bias, axes=[0]), axes=[0])


def transformer(src_vocab_size=10000, trg_vocab_size=10000, max_length=64,
                n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
                d_inner_hid=2048, dropout=0.1, label_smooth_eps=0.1,
                use_flash=False, use_fused_ce=False, fused_qkv=False,
                moe_experts=0, moe_aux_weight=0.01, flash_pallas=None,
                recompute=False, pipeline=False, flash_cross=False,
                head_major=False):
    """Build the full training graph; returns (avg_cost, logits, feeds).
    moe_experts > 0 swaps every FFN sublayer for a switch-MoE block
    (experts sharded over mp/ep) and folds the load-balance aux losses
    into the objective with weight moe_aux_weight.  recompute=True
    wraps every encoder/decoder layer in fluid.recompute_scope
    (activations rematerialized in the backward — HBM for FLOPs).
    pipeline=True tags the encoder and decoder stacks as two
    fluid.pipeline_scope groups: on a mesh with a "pp" axis each stack
    runs as a GPipe schedule over the pp stages
    (parallel/pipeline_engine.py); on other meshes the tags are inert.
    head_major=True keeps every attention activation in the flash
    kernels' head-major head-grouped layout end-to-end (no transpose at
    any kernel boundary, docs/LAYOUT.md); it requires the flash op
    (use_flash=True) and routes decoder CROSS attention through it
    too."""
    import contextlib

    from ..core.program import (pipeline_scope, pipeline_segment,
                                recompute_scope)

    def stack_scope():
        return pipeline_scope() if pipeline else contextlib.nullcontext()

    def layer_scope():
        ctx = contextlib.ExitStack()
        if pipeline:
            ctx.enter_context(pipeline_segment())
        if recompute:
            ctx.enter_context(recompute_scope())
        return ctx

    if head_major and not use_flash:
        raise ValueError(
            "head_major=True requires use_flash=True: the composed "
            "matmul+softmax attention path would reintroduce the "
            "boundary transposes the head-major layout deletes")

    moe_aux: list = []
    src_word = layers.data(name="src_word", shape=[max_length],
                           dtype="int64")
    trg_word = layers.data(name="trg_word", shape=[max_length],
                           dtype="int64")
    lbl_word = layers.data(name="lbl_word", shape=[max_length],
                           dtype="int64")
    src_len = layers.data(name="src_len", shape=[], dtype="int32")
    trg_len = layers.data(name="trg_len", shape=[], dtype="int32")

    src_bias = _padding_bias(src_len, max_length)
    trg_pad_bias = _padding_bias(trg_len, max_length)
    if use_flash:
        # flash path: decoder self-attn takes the key-padding bias +
        # the op's causal flag (the Pallas kernel's native form; the
        # XLA path inside the op applies the same mask)
        self_bias = trg_pad_bias
        self_causal = True
    else:
        causal = _causal_bias(max_length)
        self_bias = layers.elementwise_add(trg_pad_bias, causal)
        self_causal = False

    # encoder
    enc_in = _prepare_input(src_word, src_vocab_size, d_model, max_length,
                            dropout, "src_word_emb")
    x = enc_in
    with stack_scope():
        for _ in range(n_layer):
            with layer_scope():
                x = encoder_layer(x, src_bias, n_head, d_key, d_value,
                                  d_model, d_inner_hid, dropout,
                                  use_flash=use_flash,
                                  fused_qkv=fused_qkv,
                                  moe_experts=moe_experts,
                                  aux_list=moe_aux,
                                  flash_pallas=flash_pallas,
                                  head_major=head_major)
    enc_out = pre_post_process(None, x, "n")

    # decoder
    dec_in = _prepare_input(trg_word, trg_vocab_size, d_model, max_length,
                            dropout, "trg_word_emb")
    y = dec_in
    with stack_scope():
        for _ in range(n_layer):
            with layer_scope():
                y = decoder_layer(y, enc_out, self_bias, src_bias,
                                  n_head, d_key, d_value, d_model,
                                  d_inner_hid, dropout,
                                  use_flash=use_flash,
                                  fused_qkv=fused_qkv,
                                  moe_experts=moe_experts,
                                  aux_list=moe_aux,
                                  flash_pallas=flash_pallas,
                                  self_causal=self_causal,
                                  flash_cross=flash_cross,
                                  head_major=head_major)
    dec_out = pre_post_process(None, y, "n")

    if use_fused_ce:
        # fused projection+CE (ops/pallas/vocab_ce.py): the (tokens,
        # vocab) logits never hit HBM.  The weight is created directly
        # so the fused op owns the projection; a logits var is still
        # produced for the API (decode paths) via the same weight.
        from ..layer_helper import LayerHelper

        helper = LayerHelper("vocab_proj")
        proj_w = helper.create_parameter(
            None, shape=[d_model, trg_vocab_size], dtype="float32")
        cost_tok = layers.fused_vocab_softmax_ce(
            dec_out, proj_w, lbl_word, epsilon=label_smooth_eps,
            use_pallas=True)
        logits = layers.matmul(dec_out, proj_w)
        tmask = layers.sequence_mask(trg_len, maxlen=max_length,
                                     dtype="float32")
        cost = layers.elementwise_mul(cost_tok, tmask)
        sum_cost = layers.reduce_sum(cost)
        token_num = layers.reduce_sum(tmask)
        avg_cost = layers.elementwise_div(sum_cost, token_num)
        avg_cost = _fold_moe_aux(avg_cost, moe_aux, moe_aux_weight)
        feeds = ["src_word", "trg_word", "lbl_word", "src_len",
                 "trg_len"]
        return avg_cost, logits, feeds

    logits = layers.fc(dec_out, size=trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False)

    if label_smooth_eps:
        # measured on v5e: XLA fuses this one_hot composition into MXU
        # contractions (~152k tok/s) and beats the gather-based fused
        # label_smooth_eps CE (~145k tok/s) — vocab-dim gathers are slow
        # on TPU, dense one_hot contractions are not
        label = layers.label_smooth(
            layers.one_hot(lbl_word, depth=trg_vocab_size),
            epsilon=label_smooth_eps)
        cost = layers.softmax_with_cross_entropy(logits, label,
                                                 soft_label=True)
    else:
        lbl3 = layers.unsqueeze(lbl_word, axes=[2])
        cost = layers.softmax_with_cross_entropy(logits, lbl3)

    # mask padded target positions out of the loss
    tmask = layers.sequence_mask(trg_len, maxlen=max_length,
                                 dtype="float32")
    cost = layers.elementwise_mul(layers.squeeze(cost, axes=[2]), tmask)
    sum_cost = layers.reduce_sum(cost)
    token_num = layers.reduce_sum(tmask)
    avg_cost = layers.elementwise_div(sum_cost, token_num)
    avg_cost = _fold_moe_aux(avg_cost, moe_aux, moe_aux_weight)
    feeds = ["src_word", "trg_word", "lbl_word", "src_len", "trg_len"]
    return avg_cost, logits, feeds


def build_model(src_vocab_size=10000, trg_vocab_size=10000, max_length=64,
                n_layer=6, n_head=8, d_model=512, d_inner_hid=2048,
                dropout=0.1, learning_rate=2.0, warmup_steps=4000,
                with_optimizer=True, label_smooth_eps=0.1, use_flash=False,
                use_amp=False, use_fused_ce=False, fused_qkv=False,
                moe_experts=0, flash_pallas=None, recompute=False,
                pipeline=False, flash_cross=False, head_major=False):
    avg_cost, logits, feeds = transformer(
        src_vocab_size, trg_vocab_size, max_length, n_layer, n_head,
        d_model // n_head, d_model // n_head, d_model, d_inner_hid,
        dropout, label_smooth_eps, use_flash=use_flash,
        use_fused_ce=use_fused_ce, fused_qkv=fused_qkv,
        moe_experts=moe_experts, flash_pallas=flash_pallas,
        recompute=recompute, pipeline=pipeline,
        flash_cross=flash_cross, head_major=head_major)
    if with_optimizer:
        lr = layers.noam_decay(d_model, warmup_steps)
        lr = layers.elementwise_mul(
            lr, layers.fill_constant([1], "float32", learning_rate))
        opt = optimizer.AdamOptimizer(learning_rate=lr, beta1=0.9,
                                      beta2=0.997, epsilon=1e-9)
        if use_amp:
            from .. import amp as amp_mod

            opt = amp_mod.decorate(opt)
        opt.minimize(avg_cost)
    return {"loss": avg_cost, "logits": logits, "feeds": feeds}


def make_fake_batch(batch_size, max_length=64, src_vocab=10000,
                    trg_vocab=10000, seed=0):
    """Synthetic NMT batch for benchmarking (reference --use_fake_data)."""
    rng = np.random.RandomState(seed)
    src = rng.randint(1, src_vocab, (batch_size, max_length)).astype(np.int64)
    trg = rng.randint(1, trg_vocab, (batch_size, max_length)).astype(np.int64)
    lbl = rng.randint(1, trg_vocab, (batch_size, max_length)).astype(np.int64)
    src_len = np.full((batch_size,), max_length, np.int32)
    trg_len = np.full((batch_size,), max_length, np.int32)
    return {"src_word": src, "trg_word": trg, "lbl_word": lbl,
            "src_len": src_len, "trg_len": trg_len}
