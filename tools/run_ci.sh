#!/bin/sh
# CI entry (reference analog: paddle/scripts/paddle_build.sh).
# Runs the full gate: native build, test suite on the virtual 8-device
# CPU mesh, API-stability diff, multichip dryrun compile check.
set -e
cd "$(dirname "$0")/.."

echo "== native components =="
sh paddle_tpu/native/build.sh
sh paddle_tpu/native/build_demo.sh

echo "== tests (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q

echo "== API stability =="
python tools/diff_api.py

echo "== multichip dryrun (8 virtual devices) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

echo "== telemetry bench smoke (cpu) =="
# every bench JSON line must carry the observe fields
# (compile_s/retraces/peak_mem_bytes + run provenance) — docs/OBSERVE.md
BENCH_PLATFORM=cpu python - <<'EOF'
import json, subprocess, sys
r = subprocess.run(
    [sys.executable, "bench.py", "--model", "deepfm", "--batch", "64",
     "--steps", "2", "--warmup", "1", "--probe-timeout", "0"],
    capture_output=True, text=True, timeout=900)
lines = [ln for ln in r.stdout.splitlines() if ln.strip().startswith("{")]
assert lines, "bench printed no JSON line:\n" + (r.stderr or r.stdout)[-2000:]
out = json.loads(lines[-1])
assert out["compile_s"] > 0, out.get("compile_s")
with open("/tmp/bench_ci_line.json", "w") as f:
    f.write(lines[-1])
print("telemetry smoke OK:",
      {k: out.get(k) for k in ("compile_s", "retraces", "peak_mem_bytes")})
EOF

echo "== perf gate (schema + synthetic-regression smoke, cpu) =="
# 1. the fresh bench line must satisfy the observability schema
python tools/perf_gate.py --schema --candidate /tmp/bench_ci_line.json
# 2. the gate logic must actually catch a regression: a synthetic 10%
#    throughput/MFU drop against the recorded chip baseline -> exit 1;
#    the unmodified baseline against itself -> exit 0
python - <<'EOF'
import json, subprocess, sys
sys.path.insert(0, "tools")
from perf_gate import load_bench_artifact
base = load_bench_artifact("BENCH_r05.json")
ok = {"metric": "ci_smoke", "value": 1, "detail": base["detail"]}
json.dump(ok, open("/tmp/perf_gate_ok.json", "w"))
bad = json.loads(json.dumps(ok))
for m in bad["detail"].values():
    for k in ("tokens_per_sec", "imgs_per_sec", "examples_per_sec",
              "mfu"):
        if k in m:
            m[k] *= 0.9
json.dump(bad, open("/tmp/perf_gate_bad.json", "w"))
gate = [sys.executable, "tools/perf_gate.py", "--baseline",
        "BENCH_r05.json", "--candidate"]
r = subprocess.run(gate + ["/tmp/perf_gate_ok.json"],
                   capture_output=True, text=True)
assert r.returncode == 0, "gate false-failed:\n" + r.stderr
r = subprocess.run(gate + ["/tmp/perf_gate_bad.json"],
                   capture_output=True, text=True)
assert r.returncode == 1, \
    f"gate MISSED a 10% synthetic regression (rc={r.returncode}):\n" \
    + r.stdout + r.stderr
print("perf gate smoke OK: clean pass + synthetic 10% regression "
      "caught")
EOF

echo "CI OK"
