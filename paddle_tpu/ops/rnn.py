"""Recurrent ops: LSTM / GRU over padded batches with lax.scan.

reference: paddle/fluid/operators/lstm_op.cc, gru_op.cc, lstm_unit_op.cc,
gru_unit_op.cc, row_conv_op.cc + math/lstm_compute, math/gru_compute.
The reference consumes LoD (concatenated variable-length) batches via
sequence2batch reordering; here batches are padded (N, T, ...) with an
optional SeqLen companion (segment-based ragged support, SURVEY.md §5.7)
and recurrence is lax.scan — XLA unrolls onto the MXU per step, and padded
steps are masked so states freeze past each sequence's end.

Gate layouts follow the reference exactly: dynamic_lstm gates are
[candidate, input, forget, output] (lstm_op.cc:131 "Bias = {b_c, b_i,
b_f, b_o}", lstm_cpu_kernel.h:50-53 value_in/ig/fg/og); lstm_unit gates
are [input, forget, output, candidate] (lstm_unit_op.h:63-66); GRU gates
are [update, reset | candidate] with h = (1-u)*h_prev + u*c
(math/detail/gru_kernel.h).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first, opt_in, out


def _act(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda v: v,
    }[name]


@register_op("dynamic_lstm")
def dynamic_lstm(ctx, ins, attrs):
    """Input (N, T, 4H) — already projected by the preceding fc, matching
    the reference contract (lstm_op.cc expects x @ W_x done outside).
    Weight (H, 4H) recurrent projection; Bias (1, 4H) or (1, 7H) with
    peepholes.

    attrs `unroll` (lax.scan unroll factor, default 1) and `use_pallas`
    (route the recurrence through the blocked fused Pallas kernel,
    ops/pallas/recurrence.py) are the two scan-bound perf levers; the
    kernel path rejects peepholes and non-default activations loudly
    and is numerically parity-tested against the scan path
    (tests/test_pallas_recurrence.py)."""
    from .sequence import _reject_nested

    _reject_nested(ins, "dynamic_lstm")
    x = first(ins, "Input")
    w = first(ins, "Weight")
    bias = opt_in(ins, "Bias")
    seq_len = opt_in(ins, "SeqLen")
    h0 = opt_in(ins, "H0")
    c0 = opt_in(ins, "C0")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    use_peepholes = attrs.get("use_peepholes", False)
    is_reverse = attrs.get("is_reverse", False)
    unroll = int(attrs.get("unroll", 1))
    use_pallas = bool(attrs.get("use_pallas", False))

    n, t, g4 = x.shape
    h_dim = g4 // 4
    w_ic = w_fc = w_oc = jnp.zeros((h_dim,), x.dtype)
    if bias is not None:
        b_gates = bias.reshape(-1)[: 4 * h_dim]
        x = x + b_gates
        if use_peepholes:
            peep = bias.reshape(-1)[4 * h_dim: 7 * h_dim]
            w_ic = peep[:h_dim]
            w_fc = peep[h_dim: 2 * h_dim]
            w_oc = peep[2 * h_dim:]
    h_prev = h0 if h0 is not None else jnp.zeros((n, h_dim), x.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((n, h_dim), x.dtype)

    if use_pallas:
        from .pallas.recurrence import fused_lstm

        # fused_lstm itself rejects peepholes / non-default activations
        # loudly; x already carries the bias
        hs_b, cs_b, h_last, c_last = fused_lstm(
            x, w, h0=h_prev, c0=c_prev, seq_len=seq_len,
            is_reverse=is_reverse, use_peepholes=use_peepholes,
            gate_activation=attrs.get("gate_activation", "sigmoid"),
            cell_activation=attrs.get("cell_activation", "tanh"),
            candidate_activation=attrs.get("candidate_activation",
                                           "tanh"))
        return {"Hidden": [hs_b], "Cell": [cs_b],
                "LastH": [h_last], "LastC": [c_last]}

    xs = jnp.swapaxes(x, 0, 1)  # (T, N, 4H)
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
    steps = jnp.arange(t)
    if is_reverse:
        steps = jnp.flip(steps)

    def step(carry, inp):
        h, c = carry
        xt, tidx = inp
        gates = xt + h @ w
        # reference order: candidate, input, forget, output
        cand, i, f, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i = gate_act(i)
        f = gate_act(f)
        c_new = f * c + i * cand_act(cand)
        if use_peepholes:
            o = o + c_new * w_oc
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        if seq_len is not None:
            valid = (tidx < seq_len)[:, None]
            h_new = jnp.where(valid, h_new, h)
            c_new = jnp.where(valid, c_new, c)
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = lax.scan(step, (h_prev, c_prev),
                                          (xs, steps), unroll=unroll)
    if is_reverse:
        hs = jnp.flip(hs, axis=0)
        cs = jnp.flip(cs, axis=0)
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
        "LastH": [h_last],
        "LastC": [c_last],
    }


@register_op("dynamic_gru")
def dynamic_gru(ctx, ins, attrs):
    """Input (N, T, 3H) pre-projected; Weight is the recurrent
    (H, 3H) = [update|reset | candidate] split like gru_op.cc."""
    from .sequence import _reject_nested

    _reject_nested(ins, "dynamic_gru")
    x = first(ins, "Input")
    w = first(ins, "Weight")
    bias = opt_in(ins, "Bias")
    seq_len = opt_in(ins, "SeqLen")
    h0 = opt_in(ins, "H0")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    is_reverse = attrs.get("is_reverse", False)
    unroll = int(attrs.get("unroll", 1))

    n, t, g3 = x.shape
    h_dim = g3 // 3
    if bias is not None:
        x = x + bias.reshape(-1)
    w_ur = w[:, : 2 * h_dim]
    w_c = w[:, 2 * h_dim:]
    h_prev = h0 if h0 is not None else jnp.zeros((n, h_dim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
    steps = jnp.arange(t)
    if is_reverse:
        steps = jnp.flip(steps)

    def step(h, inp):
        xt, tidx = inp
        x_ur = xt[:, : 2 * h_dim]
        x_c = xt[:, 2 * h_dim:]
        ur = gate_act(x_ur + h @ w_ur)
        u, r = jnp.split(ur, 2, axis=-1)
        c = cand_act(x_c + (r * h) @ w_c)
        # reference convention (math/detail/gru_kernel.h:62):
        # h = (1-u)*h_prev + u*candidate
        h_new = (1 - u) * h + u * c
        if seq_len is not None:
            valid = (tidx < seq_len)[:, None]
            h_new = jnp.where(valid, h_new, h)
        return h_new, h_new

    h_last, hs = lax.scan(step, h_prev, (xs, steps), unroll=unroll)
    if is_reverse:
        hs = jnp.flip(hs, axis=0)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "LastH": [h_last]}


@register_op("lstm_unit")
def lstm_unit(ctx, ins, attrs):
    """Single-step LSTM cell (reference lstm_unit_op.cc): X = gates
    (N, 4H), C_prev (N, H)."""
    x, c_prev = first(ins, "X"), first(ins, "C_prev")
    forget_bias = attrs.get("forget_bias", 0.0)
    # reference order (lstm_unit_op.h:63-66): input, forget, output, cand
    i, f, o, cand = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + \
        jax.nn.sigmoid(i) * jnp.tanh(cand)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register_op("gru_unit")
def gru_unit(ctx, ins, attrs):
    x = first(ins, "Input")
    h_prev = first(ins, "HiddenPrev")
    w = first(ins, "Weight")
    bias = opt_in(ins, "Bias")
    h_dim = h_prev.shape[-1]
    gate_act = _act({1: "sigmoid", 2: "tanh", 0: "identity",
                     3: "relu"}.get(attrs.get("gate_activation", 1),
                                    "sigmoid")
                    if isinstance(attrs.get("gate_activation", 1), int)
                    else attrs.get("gate_activation", "sigmoid"))
    cand_act = _act({1: "sigmoid", 2: "tanh", 0: "identity",
                     3: "relu"}.get(attrs.get("activation", 2), "tanh")
                    if isinstance(attrs.get("activation", 2), int)
                    else attrs.get("activation", "tanh"))
    g = x
    if bias is not None:
        g = g + bias.reshape(-1)
    w_ur = w[:, : 2 * h_dim]
    w_c = w[:, 2 * h_dim:]
    ur = gate_act(g[:, : 2 * h_dim] + h_prev @ w_ur)
    u, r = jnp.split(ur, 2, axis=-1)
    c = cand_act(g[:, 2 * h_dim:] + (r * h_prev) @ w_c)
    # reference convention (gru_unit_op.h:116): h = (1-u)*h_prev + u*c
    h = (1 - u) * h_prev + u * c
    return {"Hidden": [h], "Gate": [jnp.concatenate([ur, c], -1)],
            "ResetHiddenPrev": [r * h_prev]}


@register_op("lstmp")
def lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (reference lstmp_op.cc): the
    hidden state h (size D) is projected to r (size P) each step and r —
    not h — feeds the recurrence.  Input (N, T, 4D) pre-projected like
    dynamic_lstm; Weight (P, 4D) recurrent-on-projection; ProjWeight
    (D, P); Bias (1, 4D) or (1, 7D) with peepholes.  Outputs the
    projection sequence (N, T, P) and cell sequence (N, T, D)."""
    from .sequence import _reject_nested

    _reject_nested(ins, "lstmp")
    x = first(ins, "Input")
    w = first(ins, "Weight")
    w_proj = first(ins, "ProjWeight")
    bias = opt_in(ins, "Bias")
    seq_len = opt_in(ins, "SeqLen")
    h0 = opt_in(ins, "H0")
    c0 = opt_in(ins, "C0")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    proj_act = _act(attrs.get("proj_activation", "tanh"))
    use_peepholes = attrs.get("use_peepholes", False)
    is_reverse = attrs.get("is_reverse", False)
    unroll = int(attrs.get("unroll", 1))

    n, t, g4 = x.shape
    h_dim = g4 // 4
    p_dim = w_proj.shape[1]
    w_ic = w_fc = w_oc = jnp.zeros((h_dim,), x.dtype)
    if bias is not None:
        x = x + bias.reshape(-1)[: 4 * h_dim]
        if use_peepholes:
            peep = bias.reshape(-1)[4 * h_dim: 7 * h_dim]
            w_ic, w_fc, w_oc = (peep[:h_dim], peep[h_dim: 2 * h_dim],
                                peep[2 * h_dim:])
    # initial recurrent input is the projection of H0 (OrderedP0)
    r_prev = proj_act(h0 @ w_proj) if h0 is not None \
        else jnp.zeros((n, p_dim), x.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((n, h_dim), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    steps = jnp.arange(t)
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
        steps = jnp.flip(steps)

    def step(carry, inp):
        r, c = carry
        xt, tidx = inp
        gates = xt + r @ w
        cand, i, f, o = jnp.split(gates, 4, axis=-1)  # reference order
        if use_peepholes:
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = gate_act(i), gate_act(f)
        c_new = f * c + i * cand_act(cand)
        if use_peepholes:
            o = o + c_new * w_oc
        h_new = gate_act(o) * cell_act(c_new)
        r_new = proj_act(h_new @ w_proj)
        if seq_len is not None:
            valid = (tidx < seq_len)[:, None]
            r_new = jnp.where(valid, r_new, r)
            c_new = jnp.where(valid, c_new, c)
        return (r_new, c_new), (r_new, c_new)

    (r_last, c_last), (rs, cs) = lax.scan(step, (r_prev, c_prev),
                                          (xs, steps), unroll=unroll)
    if is_reverse:
        rs = jnp.flip(rs, axis=0)
        cs = jnp.flip(cs, axis=0)
    return {
        "Projection": [jnp.swapaxes(rs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
        "LastH": [r_last],
        "LastC": [c_last],
    }


@register_op("attention_lstm")
def attention_lstm(ctx, ins, attrs):
    """Fused attention-LSTM (reference: operators/attention_lstm_op.cc,
    a jit-fused CPU kernel).  Per step, an additive attention over the
    sequence's OWN inputs conditioned on the previous cell produces the
    LSTM input:
      score_j = relu(x_j @ aw[:M] + ab + c_{t-1} @ aw[M:])
      [score = relu(scalar * score + scalar_bias)]   (optional)
      p = softmax over valid j;   lstm_x = sum_j p_j x_j
    then one standard LSTM step.  LSTMWeight is (D+M, 4D) with rows
    [hidden; input] and gate order [forget, input, output, candidate]
    (the reference's concat order).  X is padded (N, T, M) with the
    SeqLen companion instead of LoD; Hidden/Cell are (N, T, D)."""
    from .sequence import _reject_nested

    _reject_nested(ins, "attention_lstm")
    x = first(ins, "X")
    c0 = first(ins, "C0")
    h0 = opt_in(ins, "H0")
    aw = first(ins, "AttentionWeight")
    ab = opt_in(ins, "AttentionBias")
    a_scalar = opt_in(ins, "AttentionScalar")
    a_scalar_b = opt_in(ins, "AttentionScalarBias")
    lw = first(ins, "LSTMWeight")
    lb = first(ins, "LSTMBias")
    seq_len = opt_in(ins, "SeqLen")
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))

    n, t, m = x.shape
    d = lw.shape[1] // 4
    w_h, w_x = lw[:d], lw[d:]
    aw = aw.reshape(-1)
    aw_x, aw_c = aw[:m], aw[m:]
    if seq_len is None:
        valid = jnp.ones((n, t), bool)
    else:
        valid = jnp.arange(t)[None, :] < seq_len[:, None]

    # attention's x-projection is step-invariant: hoist out of the scan
    att_x = x @ aw_x  # (N, T)
    if ab is not None:
        att_x = att_x + ab.reshape(-1)[0]

    h_prev = h0 if h0 is not None else jnp.zeros((n, d), x.dtype)
    c_prev = c0

    def step(carry, _):
        h, c = carry
        score = jnp.maximum(att_x + (c @ aw_c)[:, None], 0.0)
        if a_scalar is not None:
            score = score * a_scalar.reshape(-1)[0]
            if a_scalar_b is not None:
                score = score + a_scalar_b.reshape(-1)[0]
            score = jnp.maximum(score, 0.0)
        # finite mask value, NOT -inf: a seq_len==0 row would make the
        # softmax all-(-inf) -> NaN, and the NaN survives into weight
        # grads through the backward even though the forward output is
        # masked.  With -1e30 the row softmaxes to uniform, then p is
        # zeroed so the row contributes nothing either way.
        score = jnp.where(valid, score, -1e30)
        p = jnp.where(valid, jax.nn.softmax(score, axis=1), 0.0)
        lstm_x = jnp.einsum("nt,ntm->nm", p, x)
        gates = lstm_x @ w_x + h @ w_h + lb.reshape(-1)
        f, i, o, cand = jnp.split(gates, 4, axis=-1)
        c_new = gate_act(f) * c + gate_act(i) * cand_act(cand)
        h_new = gate_act(o) * cell_act(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = lax.scan(step, (h_prev, c_prev), None, length=t)
    hs = jnp.swapaxes(hs, 0, 1)  # (N, T, D)
    cs = jnp.swapaxes(cs, 0, 1)
    # zero padded steps so downstream sequence pools see clean tails
    hs = jnp.where(valid[..., None], hs, 0.0)
    cs = jnp.where(valid[..., None], cs, 0.0)
    return {"Hidden": [hs], "Cell": [cs]}


@register_op("row_conv")
def row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference row_conv_op.cc): X (N, T, D),
    Filter (future_context, D)."""
    x, f = first(ins, "X"), first(ins, "Filter")
    ctx_len = f.shape[0]
    n, t, d = x.shape
    padded = jnp.pad(x, ((0, 0), (0, ctx_len - 1), (0, 0)))
    o = jnp.zeros_like(x)
    for k in range(ctx_len):
        o = o + padded[:, k: k + t, :] * f[k]
    return out(Out=o)
