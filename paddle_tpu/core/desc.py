"""Serializable program IR: VarDesc / OpDesc.

TPU-native analog of the reference's protobuf ProgramDesc layer
(reference: paddle/fluid/framework/framework.proto:43-189 — ProgramDesc,
BlockDesc, OpDesc, VarDesc messages). We keep the same conceptual split —
a serializable description of variables and operators — but the descs are
plain dataclasses serialized to JSON, and the "interpreter" is a tracing
compiler (see core/executor.py) that lowers the whole program to one XLA
computation instead of running ops one by one.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

# Program format version, mirroring the version field of the reference proto
# (reference: paddle/fluid/framework/framework.proto:24) so checkpoints and
# exported inference programs can be compatibility-checked on load.
PROGRAM_FORMAT_VERSION = 1

# Canonical dtype names (string form of jnp dtypes).
_DTYPE_ALIASES = {
    "float": "float32",
    "fp32": "float32",
    "fp16": "float16",
    "bf16": "bfloat16",
    "double": "float64",
    "int": "int32",
    "long": "int64",
    "bool": "bool",
    "uint8": "uint8",
    "int8": "int8",
    "int16": "int16",
    "int32": "int32",
    "int64": "int64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "float32": "float32",
    "float64": "float64",
}


def normalize_dtype(dtype) -> str:
    """Normalize a dtype spec (str / np.dtype / jnp dtype) to canonical str."""
    if dtype is None:
        return "float32"
    name = getattr(dtype, "name", None) or str(dtype)
    name = name.replace("np.", "").replace("jnp.", "")
    if name in _DTYPE_ALIASES:
        return _DTYPE_ALIASES[name]
    raise ValueError(f"unsupported dtype: {dtype!r}")


@dataclasses.dataclass
class VarDesc:
    """Description of a program variable.

    Mirrors reference VarDesc (framework.proto:105-165): name, type, shape,
    dtype, persistable.  LoD level is replaced by `lod_level` meaning "has a
    companion sequence-length tensor" (segment/length based ragged support
    instead of LoD offset tables, see SURVEY.md §5.7).
    """

    name: str
    shape: Tuple[int, ...] = ()
    dtype: str = "float32"
    persistable: bool = False
    stop_gradient: bool = False
    is_data: bool = False
    lod_level: int = 0
    # Parameter-only metadata (regularizer/clip live on the python Parameter).
    is_parameter: bool = False
    trainable: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "VarDesc":
        d = dict(d)
        d["shape"] = tuple(d.get("shape", ()))
        return VarDesc(**d)


@dataclasses.dataclass
class OpDesc:
    """Description of one operator invocation.

    Mirrors reference OpDesc (framework.proto:75-104): type plus named
    input/output slots (each a list of var names) and an attribute map.
    Attrs must be JSON-serializable.
    """

    type: str
    inputs: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    outputs: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def input_names(self) -> List[str]:
        out: List[str] = []
        for names in self.inputs.values():
            out.extend(names)
        return out

    def output_names(self) -> List[str]:
        out: List[str] = []
        for names in self.outputs.values():
            out.extend(names)
        return out

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "OpDesc":
        return OpDesc(
            type=d["type"],
            inputs={k: list(v) for k, v in d.get("inputs", {}).items()},
            outputs={k: list(v) for k, v in d.get("outputs", {}).items()},
            attrs=dict(d.get("attrs", {})),
        )


def dump_program_dict(prog_dict: Dict[str, Any]) -> str:
    """Serialize a program dict (from Program.to_dict) to JSON text."""
    return json.dumps(prog_dict, indent=1, sort_keys=True)


def load_program_dict(text: str) -> Dict[str, Any]:
    d = json.loads(text)
    version = d.get("version", 0)
    if version > PROGRAM_FORMAT_VERSION:
        raise RuntimeError(
            f"program format version {version} is newer than supported "
            f"({PROGRAM_FORMAT_VERSION})"
        )
    return d
