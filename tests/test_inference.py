"""Inference serving + quantization tests.

reference patterns: inference/tests/api/analyzer_*_tester.cc (predictor
output vs native executor, latency), contrib/tests/test_quantize_transpiler.py.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _build_and_train(scope, steps=3):
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], append_batch_size=False)
        y = layers.data("y", shape=[8, 1], append_batch_size=False)
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(steps):
            exe.run(main, feed={
                "x": rng.rand(8, 16).astype(np.float32),
                "y": rng.rand(8, 1).astype(np.float32)}, fetch_list=[loss])
    return main, pred


def test_predictor_bit_identical_and_warm(tmp_path):
    scope = fluid.Scope()
    main, pred = _build_and_train(scope)
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(str(tmp_path), ["x"], [pred], exe,
                                      main_program=main)
        xv = np.random.RandomState(1).rand(8, 16).astype(np.float32)
        infer_prog, feeds, fetches = fluid.io.load_inference_model(
            str(tmp_path), exe)
        (ref,) = exe.run(infer_prog, feed={"x": xv}, fetch_list=fetches)

    predictor = fluid.Predictor(str(tmp_path))
    assert predictor.get_input_names() == ["x"]
    (got,) = predictor.run({"x": xv})
    np.testing.assert_array_equal(got, ref)  # bit-identical contract
    # warm path reuses the AOT executable (no recompilation): same result
    (got2,) = predictor.run({"x": xv})
    np.testing.assert_array_equal(got2, ref)
    # positional-input API
    (got3,) = predictor.run([xv])
    np.testing.assert_array_equal(got3, ref)
    stats = predictor.benchmark({"x": xv}, iters=5, warmup=1)
    assert stats["p50_ms"] > 0


def test_serialized_export_roundtrip(tmp_path):
    scope = fluid.Scope()
    main, pred = _build_and_train(scope)
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(str(tmp_path), ["x"], [pred], exe,
                                      main_program=main)
    xv = np.random.RandomState(2).rand(8, 16).astype(np.float32)
    path = fluid.inference.export_serialized_model(
        str(tmp_path), {"x": xv})
    assert os.path.exists(path)

    ref = fluid.Predictor(str(tmp_path)).run({"x": xv})[0]
    p = fluid.Predictor(str(tmp_path))
    assert p._exported is not None and p._export_sig is not None
    (got,) = p.run({"x": xv})
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # a float64-typed input must NOT be routed to the float32 artifact;
    # the traced fallback serves it (jnp casts to f32 on conversion)
    (got64,) = p.run({"x": xv.astype(np.float64)})
    np.testing.assert_allclose(got64, ref, rtol=1e-6)
    # mismatched shape falls back to the traced path and still works
    xv2 = np.random.RandomState(3).rand(4, 16).astype(np.float32)
    # program declares batch 8; retrace handles shape only if program
    # allows — here declared static, so expect an error rather than
    # silent wrong output
    with pytest.raises(Exception):
        p.run({"x": np.random.rand(8, 17).astype(np.float32)})


def test_quantize_transpiler_training_and_parity():
    rng = np.random.RandomState(4)
    B = 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, 16], append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        t = fluid.QuantizeTranspiler()
        t.training_transpile(main, startup)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    qtypes = [op.type for op in main.global_block().ops
              if op.type.startswith("fake_quantize")]
    # 2 mul ops × (activation + weight) = 4 insertions
    assert len(qtypes) == 4, qtypes
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"x": rng.rand(B, 16).astype(np.float32),
                "y": rng.rand(B, 1).astype(np.float32)}
        losses = [float(exe.run(main, feed=feed,
                                fetch_list=[loss])[0].reshape(()))
                  for _ in range(15)]
        # moving-average scale state updated and persisted
        state_names = [n for n in scope.vars if "quant_scale_state" in n]
        assert state_names
        assert float(np.asarray(scope.find_var(state_names[0]))) > 0
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_quantize_rejects_after_backward():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8], append_batch_size=False)
        pred = layers.fc(x, size=1)
        loss = layers.reduce_mean(pred)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        with pytest.raises(RuntimeError):
            fluid.QuantizeTranspiler().training_transpile(main, startup)


def test_quantized_clone_for_test_freezes_scales():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8], append_batch_size=False)
        pred = layers.fc(x, size=1)
        fluid.QuantizeTranspiler().training_transpile(main, startup)
    test_prog = main.clone(for_test=True)
    ops = [op for op in test_prog.global_block().ops
           if op.type == "fake_quantize_dequantize_moving_average_abs_max"]
    assert ops and all(op.attrs.get("is_test") for op in ops)


def test_fake_quantize_ops_numerics():
    from tests.op_test import run_op

    x = np.array([[-1.0, 0.5, 0.25, 1.0]], np.float32)
    q = run_op("fake_quantize_abs_max", {"X": x},
               attrs={"bit_length": 8})
    np.testing.assert_allclose(q, np.round(x * 127.0), rtol=1e-6)
    scale = run_op("fake_quantize_abs_max", {"X": x},
                   attrs={"bit_length": 8}, out_slot="OutScale")
    assert scale[0] == 1.0
    dq = run_op("fake_dequantize_max_abs",
                {"X": q, "Scale": np.array([1.0], np.float32)},
                attrs={"max_range": 127.0})
    np.testing.assert_allclose(dq, np.round(x * 127.0) / 127.0, rtol=1e-6)
    # combined qdq with STE: forward = quantization grid
    qdq = run_op("fake_quantize_dequantize_abs_max", {"X": x},
                 attrs={"bit_length": 8})
    np.testing.assert_allclose(qdq, np.round(x * 127.0) / 127.0, rtol=1e-6)


def test_qdq_gradient_is_straight_through():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.registry import OpContext, get_op_impl

    impl = get_op_impl("fake_quantize_dequantize_abs_max")

    def f(x):
        o = impl(OpContext(jax.random.PRNGKey(0)), {"X": [x]},
                 {"bit_length": 8})
        return jnp.sum(o["Out"][0] * jnp.arange(4.0))

    g = jax.grad(f)(jnp.asarray([-1.0, 0.5, 0.25, 1.0]))
    np.testing.assert_allclose(np.asarray(g), np.arange(4.0), rtol=1e-6)


def test_beam_decode_exports_through_predictor(tmp_path):
    """The AOT Predictor serves a CONTROL-FLOW program: the NMT beam
    -search decode (While loop + beam ops) exports via
    save_inference_model and the Predictor's jitted run matches the
    executor's decode bit-for-bit (reference analog: exporting the
    RNN-search decoder through the inference engine)."""
    from paddle_tpu.models import machine_translation as mt

    B, Tsrc, V, K, L = 4, 8, 50, 3, 6
    scope = fluid.Scope()
    rng = np.random.RandomState(0)

    # train briefly so decode weights are non-trivial
    train_prog, train_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(train_prog, train_startup):
        avg_cost, _ = mt.seq_to_seq_net(
            src_vocab_size=V, trg_vocab_size=V, embed_dim=16,
            hidden_dim=32, batch_size=B, max_src_len=Tsrc,
            max_trg_len=7)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(train_startup)
        feed = {
            "src_word_id": rng.randint(2, V, (B, Tsrc)).astype(np.int64),
            "src_word_id.seq_len": rng.randint(
                3, Tsrc + 1, B).astype(np.int32),
            "trg_word_id": rng.randint(2, V, (B, 7)).astype(np.int64),
            "trg_word_id.seq_len": rng.randint(3, 8, B).astype(np.int32),
            "trg_next_id": rng.randint(2, V, (B, 7)).astype(np.int64),
        }
        for _ in range(3):
            exe.run(train_prog, feed=feed, fetch_list=[avg_cost])

        # decode program in the SAME scope (shares trained params)
        infer_prog, infer_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(infer_prog, infer_startup):
            sents, scores, _ = mt.beam_search_net(
                src_vocab_size=V, trg_vocab_size=V, embed_dim=16,
                hidden_dim=32, batch_size=B, max_src_len=Tsrc,
                beam_size=K, max_decode_len=L, start_id=0, end_id=1)
        dec_feed = {"src_word_id": feed["src_word_id"],
                    "src_word_id.seq_len": feed["src_word_id.seq_len"]}
        ref_s, ref_sc = exe.run(infer_prog, feed=dec_feed,
                                fetch_list=[sents, scores])

        d = str(tmp_path / "decoder")
        fluid.io.save_inference_model(
            d, ["src_word_id", "src_word_id.seq_len"], [sents, scores],
            exe, main_program=infer_prog)

    pred = fluid.Predictor(d)
    got_s, got_sc = pred.run(dec_feed)
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(ref_s))
    np.testing.assert_allclose(np.asarray(got_sc), np.asarray(ref_sc),
                               rtol=1e-5, atol=1e-6)
    assert np.asarray(got_s).shape == (B, K, L)


def test_beam_decode_stablehlo_export(tmp_path):
    """The While-loop beam decoder also survives the portable StableHLO
    export (jax.export): artifact served == traced serving."""
    from paddle_tpu.models import machine_translation as mt

    B, Tsrc, V, K, L = 2, 6, 30, 2, 4
    scope = fluid.Scope()
    rng = np.random.RandomState(5)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        infer_prog, infer_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(infer_prog, infer_startup):
            sents, scores, _ = mt.beam_search_net(
                src_vocab_size=V, trg_vocab_size=V, embed_dim=8,
                hidden_dim=16, batch_size=B, max_src_len=Tsrc,
                beam_size=K, max_decode_len=L, start_id=0, end_id=1)
        exe.run(infer_startup)
        d = str(tmp_path / "dec")
        fluid.io.save_inference_model(
            d, ["src_word_id", "src_word_id.seq_len"], [sents, scores],
            exe, main_program=infer_prog)
    feed = {"src_word_id": rng.randint(2, V, (B, Tsrc)).astype(np.int64),
            "src_word_id.seq_len": np.full((B,), Tsrc, np.int32)}
    ref = fluid.Predictor(d).run(feed)
    path = fluid.inference.export_serialized_model(d, feed)
    assert os.path.exists(path)
    p = fluid.Predictor(d)
    assert p._exported is not None
    got = p.run(feed)
    np.testing.assert_array_equal(np.asarray(got[0]),
                                  np.asarray(ref[0]))
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(ref[1]),
                               rtol=1e-5, atol=1e-6)
