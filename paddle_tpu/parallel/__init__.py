"""Distributed execution over a TPU device mesh.

TPU-native replacement for the reference's distributed stack
(SURVEY.md §2.3/§5.8): ParallelExecutor's per-device SSA graphs + NCCL
all-reduce op handles (framework/details/) become jit with
NamedSharding annotations — XLA GSPMD partitions the single program and
inserts ICI collectives; the pserver/DistributeTranspiler path is
subsumed by parameter sharding (FSDP-style) and sharded embedding
tables.
"""

from .collectives import (all_gather, all_reduce, all_to_all,  # noqa: F401
                          barrier, ppermute, psum,
                          quantized_all_reduce, reduce_scatter)
from .compiler import (BuildStrategy, CompiledProgram,  # noqa: F401
                       ExecutionStrategy)
from .dist import (global_batch, init_distributed,  # noqa: F401
                   make_multihost_mesh, shutdown_distributed)
from .mesh import get_default_mesh, make_mesh, set_default_mesh  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .pipeline import gpipe, gpipe_loss_and_grad  # noqa: F401
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
from .strategies import GradSyncConfig, ShardingRules  # noqa: F401
