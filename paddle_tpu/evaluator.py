"""Evaluator API (reference: python/paddle/fluid/evaluator.py:1).

The reference's evaluator classes were already deprecation-wrappers
around `fluid.metrics` ("Better to use fluid.metrics", evaluator.py
docstrings); here they alias the metrics accumulators directly — the
graph-side accumulator state the old Evaluator managed is covered by the
metric ops' state inputs (auc's stat buffers, precision_recall's
StatesInfo, chunk_eval's chunk counts).
"""

from .metrics import (Accuracy, Auc, ChunkEvaluator,  # noqa: F401
                      DetectionMAP, EditDistance, MetricBase)


class Evaluator(MetricBase):
    """Historical extension base (reference evaluator.py Evaluator):
    subclasses implement update()/eval() like any MetricBase."""
