"""DeepFM CTR model — the high-dimensional sparse-embedding config.

reference: BASELINE.json configs ("DeepFM CTR — high-dim sparse embedding,
pserver→ICI collective path") and the fluid CTR pattern
(python/paddle/fluid/contrib/reader/ctr_reader.py + dist lookup table,
SURVEY.md §2.3).  Sparse features are field-wise id slots; the embedding
table is a dense sharded array on TPU — sharding rules in
parallel/strategies.py shard the big table over the mesh, replacing the
reference's distributed lookup-table pserver path.
"""

from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..param_attr import ParamAttr
from ..initializer import Normal, Uniform


def build_model(num_fields=26, num_dense=13, vocab_size=1000001,
                embedding_dim=16, dnn_hidden=(400, 400, 400),
                learning_rate=1e-3, with_optimizer=True):
    sparse_ids = layers.data(name="sparse_ids", shape=[num_fields],
                             dtype="int64")
    dense_vals = layers.data(name="dense_vals", shape=[num_dense],
                             dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")

    # first-order: per-id scalar weight.  is_sparse=True engages the
    # SelectedRows-style grad path (core/executor.py): table grads are
    # (ids, rows) and Adam updates only the touched rows — the capability
    # the reference served with the distributed lookup table + sparse
    # pserver updates.
    w1 = layers.embedding(sparse_ids, size=[vocab_size, 1], is_sparse=True,
                          param_attr=ParamAttr(name="fm_w1",
                                               initializer=Normal(0, 1e-3)))
    first_order = layers.reduce_sum(layers.squeeze(w1, axes=[2]), dim=1,
                                    keep_dim=True)
    dense_w = layers.fc(dense_vals, size=1, bias_attr=False)
    first_order = layers.elementwise_add(first_order, dense_w)

    # second-order FM: 0.5 * ((sum v)^2 - sum v^2)
    emb = layers.embedding(
        sparse_ids, size=[vocab_size, embedding_dim], is_sparse=True,
        param_attr=ParamAttr(
            name="fm_emb",
            initializer=Uniform(-1.0 / embedding_dim ** 0.5,
                                1.0 / embedding_dim ** 0.5)))
    sum_emb = layers.reduce_sum(emb, dim=1)          # (N, D)
    sum_sq = layers.square(sum_emb)
    sq_emb = layers.square(emb)
    sq_sum = layers.reduce_sum(sq_emb, dim=1)
    second_order = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=1,
                          keep_dim=True), scale=0.5)

    # deep component
    deep = layers.reshape(emb, shape=[0, num_fields * embedding_dim])
    deep = layers.concat([deep, dense_vals], axis=1)
    for h in dnn_hidden:
        deep = layers.fc(deep, size=h, act="relu")
    deep_out = layers.fc(deep, size=1, bias_attr=False)

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    flabel = layers.cast(label, "float32")
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, flabel))
    prob = layers.sigmoid(logit)
    prob2 = layers.concat([layers.elementwise_sub(
        layers.fill_constant_batch_size_like(prob, [-1, 1], "float32", 1.0),
        prob), prob], axis=1)
    auc_out, _stats = layers.auc(prob2, label)
    if with_optimizer:
        opt = optimizer.AdamOptimizer(learning_rate=learning_rate)
        opt.minimize(loss)
    return {"loss": loss, "auc": auc_out,
            "feeds": ["sparse_ids", "dense_vals", "label"]}


def make_fake_batch(batch_size, num_fields=26, num_dense=13,
                    vocab_size=1000001, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "sparse_ids": rng.randint(0, vocab_size,
                                  (batch_size, num_fields)).astype(np.int64),
        "dense_vals": rng.rand(batch_size, num_dense).astype(np.float32),
        "label": rng.randint(0, 2, (batch_size, 1)).astype(np.int64),
    }
