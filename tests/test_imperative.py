"""Imperative (eager) mode tests (reference pattern:
tests/unittests/test_imperative.py for the dygraph embryo)."""

import numpy as np
import pytest

import jax

from paddle_tpu import imperative


def test_varbase_and_trace_outside_guard():
    v = imperative.to_variable(np.ones((2, 2), np.float32))
    assert v.shape == (2, 2)
    with pytest.raises(RuntimeError):
        imperative.trace_op("square", {"X": [v]})
    with pytest.raises(RuntimeError):
        v.backward()


def test_eager_grad_matches_jax_grad():
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 3).astype(np.float32)
    wv = rng.rand(3, 2).astype(np.float32)

    with imperative.guard():
        x = imperative.to_variable(xv, stop_gradient=True)
        w = imperative.to_variable(wv)
        y = imperative.trace_op("mul", {"X": [x], "Y": [w]},
                                {"x_num_col_dims": 1, "y_num_col_dims": 1})
        z = imperative.trace_op("tanh", {"X": [y]})
        loss = imperative.trace_op(
            "reduce_mean", {"X": [z]},
            {"reduce_all": True, "dim": [0], "keep_dim": False})
        loss.backward()
        got = np.asarray(w.grad)

    def f(w_):
        import jax.numpy as jnp

        return jnp.mean(jnp.tanh(xv @ w_))

    want = np.asarray(jax.grad(f)(wv))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_eager_grad_accumulates_shared_var():
    # a var consumed twice accumulates both cotangents (reference
    # tracer sums duplicate grads)
    v = np.array([1.0, 2.0], np.float32)
    with imperative.guard():
        a = imperative.to_variable(v)
        b = imperative.trace_op("elementwise_mul", {"X": [a], "Y": [a]})
        s = imperative.trace_op(
            "reduce_sum", {"X": [b]},
            {"reduce_all": True, "dim": [0], "keep_dim": False})
        s.backward()
        np.testing.assert_allclose(np.asarray(a.grad), 2 * v, rtol=1e-6)


def test_eager_fc_layer_trains():
    rng = np.random.RandomState(1)
    xv = rng.rand(8, 4).astype(np.float32)
    yv = (xv @ rng.rand(4, 1)).astype(np.float32)
    with imperative.guard() as tracer:
        fc = imperative.FC(4, 1)
        losses = []
        for _ in range(30):
            tracer.reset()
            fc.clear_gradients()
            x = imperative.to_variable(xv, stop_gradient=True)
            y = imperative.to_variable(yv, stop_gradient=True)
            d = imperative.trace_op("elementwise_sub",
                                    {"X": [fc(x)], "Y": [y]})
            sq = imperative.trace_op("square", {"X": [d]})
            loss = imperative.trace_op(
                "reduce_mean", {"X": [sq]},
                {"reduce_all": True, "dim": [0], "keep_dim": False})
            loss.backward()
            losses.append(float(loss.numpy().reshape(())))
            for p in fc.parameters():
                p.value = p.value - 0.3 * p.grad
    assert losses[-1] < losses[0] * 0.1
    assert len(fc.parameters()) == 2


def test_sublayer_parameter_collection():
    class Net(imperative.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = imperative.FC(4, 8)
            self.fc2 = imperative.FC(8, 1)

        def forward(self, x):
            return self.fc2(self.fc1(x))

    net = Net()
    assert len(net.parameters()) == 4


def test_eager_conv_and_embedding_layers():
    """Conv2D / Embedding eager layers: forward matches the op kernels,
    gradients flow to their parameters."""
    with imperative.guard():
        x = imperative.to_variable(
            np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32))
        conv = imperative.Conv2D(3, 4, 3, padding=1, act="relu")
        y = conv(x)
        assert y.shape == (2, 4, 8, 8)
        loss = imperative.trace_op("reduce_mean", {"X": [y]})
        loss.backward()
        assert conv.w.grad is not None and conv.b.grad is not None
        assert np.isfinite(np.asarray(conv.w.grad)).all()

    with imperative.guard():
        ids = imperative.to_variable(
            np.array([[1, 2], [3, 0]], np.int64), stop_gradient=True)
        emb = imperative.Embedding([10, 6])
        out = emb(ids)
        assert out.shape == (2, 2, 6)
        loss = imperative.trace_op("reduce_mean", {"X": [out]})
        loss.backward()
        assert emb.w.grad is not None


def test_eager_training_with_optimizers_converges():
    """Full eager training loop (reference dygraph mnist test pattern):
    forward -> backward -> optimizer.minimize, loss decreases; Adam
    state is per-parameter and the tape resets every step."""
    rng = np.random.RandomState(1)
    xv = rng.rand(64, 16).astype(np.float32)
    yv = (xv[:, :4].sum(1, keepdims=True) > 2.0).astype(np.float32)

    for opt in (imperative.SGDOptimizer(learning_rate=0.5),
                imperative.AdamOptimizer(learning_rate=0.05)):
        with imperative.guard() as tracer:
            l1 = imperative.FC(16, 16, act="relu")
            l2 = imperative.FC(16, 1)
            params = l1.parameters() + l2.parameters()
            losses = []
            for _ in range(80):
                x = imperative.to_variable(xv, stop_gradient=True)
                y = imperative.to_variable(yv, stop_gradient=True)
                pred = imperative.trace_op("sigmoid", {"X": [l2(l1(x))]})
                err = imperative.trace_op(
                    "elementwise_sub", {"X": [pred], "Y": [y]})
                sq = imperative.trace_op("square", {"X": [err]})
                loss = imperative.trace_op("reduce_mean", {"X": [sq]})
                losses.append(float(loss.numpy().reshape(())))
                opt.minimize(loss, params)
                assert tracer.tape == []  # reset each step
            assert losses[-1] < losses[0] * 0.6, (
                type(opt).__name__, losses[0], losses[-1])


def test_unnamed_layers_get_distinct_inits():
    """Two unnamed layers of one class must NOT share default weights
    (the deterministic seed mixes an instance counter)."""
    c1 = imperative.Conv2D(3, 4, 3)
    c2 = imperative.Conv2D(3, 4, 3)
    assert not np.array_equal(c1.w.numpy(), c2.w.numpy())
    e1 = imperative.Embedding([10, 6])
    e2 = imperative.Embedding([10, 6])
    assert not np.array_equal(e1.w.numpy(), e2.w.numpy())


def test_adam_state_drops_with_dead_params():
    """Adam moments are weakref-keyed: rebuilding the model releases
    the old parameters' state instead of leaking it."""
    import gc

    opt = imperative.AdamOptimizer(learning_rate=0.01)
    with imperative.guard():
        for _ in range(3):
            fc = imperative.FC(8, 4)
            x = imperative.to_variable(
                np.ones((2, 8), np.float32), stop_gradient=True)
            loss = imperative.trace_op("reduce_mean", {"X": [fc(x)]})
            opt.minimize(loss, fc.parameters())
            del fc, x, loss
            gc.collect()
    assert len(opt._state) <= 2  # only the LAST model's 2 params remain
