"""dp-mesh training + explicit (quantized) gradient synchronization
(ISSUE 10, docs/DIST.md).

Acceptance pins:
- dp=8 loss trajectory matches single-device at a FIXED global batch
  within a pinned tolerance (the GSPMD implicit path — the bench
  --mesh contract);
- the explicit bf16 exchange matches the implicit path (control arm);
- int8 quantized grad sync trains to a trajectory within the
  documented tolerance of bf16 dp (the EQuARX correctness A/B the
  virtual mesh can record; wall clock is a chip question);
- SparseGrad stays sparse through the exchange: the embedding-table
  gradient is never routed into the quantized dense path, and
  untouched table rows stay bit-identical (the lazy-update property);
- designed loud errors: composed meshes, gradient accumulation.

Tolerances are measured-then-pinned (see comments), not aspirational.
All models here are deliberately tiny: 8 virtual devices share one
host core, so every compile/dispatch is serialized.
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import GradSyncConfig, make_mesh
from paddle_tpu.parallel.strategies import ShardingRules

N_DEV = 8
STEPS = 6


@pytest.fixture(scope="module", autouse=True)
def _need_devices():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")


def _batches(n=STEPS, b=64, din=32, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(b, din).astype(np.float32),
             "y": rng.randn(b, 1).astype(np.float32)}
            for _ in range(n)]


def _build_mlp():
    # dropout-free on purpose: the explicit exchange folds the rank
    # index into the RNG key (per-rank dropout streams), so EXACT
    # parity claims are only meaningful for deterministic programs
    x = layers.data("x", shape=[32], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, size=128, act="relu")
    h = layers.fc(h, size=128, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _run(grad_sync, mesh_axes, batches=None, build=_build_mlp,
         accumulation_steps=1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = build()
        exe = fluid.Executor()
        exe.run(startup)
        if mesh_axes:
            bs = fluid.BuildStrategy()
            bs.grad_sync = grad_sync
            fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs,
                mesh=make_mesh(mesh_axes))
        losses = []
        for b in (batches or _batches()):
            (lv,) = exe.run(main, feed=b, fetch_list=[loss],
                            accumulation_steps=accumulation_steps)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return np.asarray(losses), scope


def test_dp_loss_parity_vs_single_device():
    """ACCEPTANCE: dp=8 (global batch fixed) vs single device.  jit
    value semantics make the partitioned step numerically equivalent
    up to reduction-order float drift; measured 7e-8 max relative over
    6 steps on this backend — pinned at 1e-5."""
    single, _ = _run(None, None)
    dp, _ = _run(None, {"dp": N_DEV})
    np.testing.assert_allclose(dp, single, rtol=1e-5, atol=1e-7)


def test_explicit_bf16_matches_implicit_dp():
    """The explicit shard_map exchange is the same math as the GSPMD
    all-reduce (psum of local-mean grads + pmean loss) — the control
    arm that isolates quantization in the int8 A/B."""
    implicit, _ = _run(None, {"dp": N_DEV})
    explicit, _ = _run("bf16", {"dp": N_DEV})
    np.testing.assert_allclose(explicit, implicit, rtol=1e-5,
                               atol=1e-7)


def test_int8_trajectory_within_documented_tolerance():
    """ACCEPTANCE: int8 quantized grad sync vs bf16 dp.  The
    documented tolerance (docs/DIST.md): per-step relative loss
    deviation under 1e-2 on this model class over 6 steps, and the
    trajectory must actually DESCEND (quantization noise must not
    masquerade as training).  Measured here: ~1e-4 after 6 steps —
    pinned with margin at 1e-2."""
    bf16, _ = _run("bf16", {"dp": N_DEV})
    int8, _ = _run(GradSyncConfig("int8"), {"dp": N_DEV})
    rel = np.abs(int8 - bf16) / np.maximum(np.abs(bf16), 1e-6)
    assert rel.max() < 1e-2, f"int8 trajectory off by {rel.max():.2e}"
    assert int8[-1] < int8[0], "int8 run did not descend"
    assert np.isfinite(int8).all()


def test_int8_quantization_is_actually_active():
    """The int8 trajectory must DIFFER from bf16 at the bit level on a
    model with above-floor tensors — otherwise the A/B would be
    comparing the exchange to itself (a floor set too high silently
    turns the feature off)."""
    bf16, _ = _run("bf16", {"dp": N_DEV})
    int8, _ = _run(GradSyncConfig("int8", min_quant_numel=1),
                   {"dp": N_DEV})
    assert not np.array_equal(int8, bf16)


def test_int8_run_is_deterministic():
    """Same seed + same feeds -> bitwise-identical trajectory: the
    quantized exchange introduces error, never nondeterminism."""
    a, _ = _run(GradSyncConfig("int8"), {"dp": N_DEV})
    b, _ = _run(GradSyncConfig("int8"), {"dp": N_DEV})
    assert np.array_equal(a, b)


# -- sparse path -----------------------------------------------------------

V, D, B, F = 64, 16, 32, 4


def _build_sparse():
    ids = layers.data("ids", shape=[B, F], dtype="int64",
                      append_batch_size=False)
    y = layers.data("y", shape=[B, 1], append_batch_size=False)
    emb = layers.embedding(
        ids, size=[V, D], is_sparse=True,
        param_attr=fluid.ParamAttr(
            name="tbl", initializer=fluid.initializer.Constant(0.05)))
    s = layers.reduce_sum(emb, dim=1)
    h = layers.fc(s, size=256, act="relu")
    p = layers.fc(h, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(p, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _sparse_batches(n=4):
    rng = np.random.RandomState(1)
    # ids drawn from the LOWER half of the vocab only: the upper half
    # must come through training untouched (the sparsity proof)
    return [{"ids": rng.randint(0, V // 2, (B, F)).astype(np.int64),
             "y": rng.rand(B, 1).astype(np.float32)}
            for _ in range(n)]


def test_sparse_grads_stay_sparse_under_int8(monkeypatch):
    """SparseGrad never enters the quantized dense exchange (ids+rows
    all_gather keeps it O(touched)), and untouched embedding rows are
    bit-identical after training — the lazy sparse-update contract,
    now across the dp exchange."""
    from paddle_tpu.parallel import collectives

    seen_shapes = []
    real = collectives.quantized_all_reduce_local

    def spy(g, *a, **kw):
        seen_shapes.append(tuple(g.shape))
        return real(g, *a, **kw)

    monkeypatch.setattr(collectives, "quantized_all_reduce_local", spy)
    batches = _sparse_batches()
    int8, scope = _run(GradSyncConfig("int8", min_quant_numel=1),
                       {"dp": N_DEV}, batches=batches,
                       build=_build_sparse)
    assert np.isfinite(int8).all() and int8[-1] < int8[0]
    # the (V, D) table gradient must never be densified into the
    # quantized path...
    assert (V, D) not in seen_shapes, seen_shapes
    # ...while the dense fc weights DO go through it
    assert any(len(s) == 2 and s[0] * s[1] >= 256 for s in seen_shapes), \
        seen_shapes
    # untouched rows: ids only ever hit [0, V/2)
    table = np.asarray(scope.find_var("tbl"))
    np.testing.assert_array_equal(
        table[V // 2:], np.full((V - V // 2, D), 0.05, np.float32))
    assert not np.allclose(table[:V // 2], 0.05)

    # and the sparse trajectory stays within the documented tolerance
    # of the bf16 exchange (same sparse handling both sides)
    bf16, _ = _run("bf16", {"dp": N_DEV}, batches=batches,
                   build=_build_sparse)
    rel = np.abs(int8 - bf16) / np.maximum(np.abs(bf16), 1e-6)
    assert rel.max() < 1e-2, rel


# -- designed errors -------------------------------------------------------

def test_grad_sync_partial_batch_falls_back_exact():
    """A final batch that does not divide dp must TRAIN (replicated
    feeds, exact grads — the feed_spec_for replicate-on-indivisible
    rule), not crash the epoch tail.  Found by driving the surface."""
    rng = np.random.RandomState(3)
    batches = _batches(3) + [
        {"x": rng.randn(13, 32).astype(np.float32),
         "y": rng.randn(13, 1).astype(np.float32)}]
    int8, _ = _run(GradSyncConfig("int8"), {"dp": N_DEV},
                   batches=batches)
    assert np.isfinite(int8).all() and len(int8) == 4


def test_grad_sync_rejects_params_sharded_over_data_axis():
    """ISSUE 13 moved the composition line: dp×mp / dp×fsdp meshes now
    TRAIN under explicit grad sync (tests/test_hybrid_parallel.py); the
    one remaining designed error is ZeRO-3-style param sharding over a
    DATA axis — the replicated param entry would silently all-gather
    the model every step."""

    def run_zero3():
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            loss = _build_mlp()
            exe = fluid.Executor()
            exe.run(startup)
            bs = fluid.BuildStrategy()
            bs.grad_sync = "int8"
            # params sharded over the batch axis (the Reduce strategy)
            bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
            fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs,
                mesh=make_mesh({"dp": N_DEV}))
            exe.run(main, feed=_batches(1)[0], fetch_list=[loss])

    with pytest.raises(ValueError, match="sharded over the data ax"):
        run_zero3()


def test_grad_sync_rejects_gradient_accumulation():
    with pytest.raises(ValueError, match="accumulation"):
        _run("int8", {"dp": N_DEV}, accumulation_steps=2)


def test_grad_sync_config_normalize():
    assert GradSyncConfig.normalize(None) is None
    cfg = GradSyncConfig.normalize("int8")
    assert cfg.mode == "int8" and cfg.block_size == 256
    assert GradSyncConfig.normalize(cfg) is cfg
    with pytest.raises(ValueError, match="not in"):
        GradSyncConfig.normalize("fp4")


# -- feed sharding rule ----------------------------------------------------

def test_feed_spec_for_data_axis():
    mesh = make_mesh({"dp": N_DEV})
    rules = ShardingRules()
    assert rules.feed_spec_for("x", (64, 32), mesh) == ("dp", None)
    # non-divisible batch replicates (final partial batch stays correct)
    assert rules.feed_spec_for("x", (3, 32), mesh) == (None, None)
    assert rules.feed_spec_for("s", (), mesh) == ()
    # an explicit rule wins over the data-axis default
    rules = ShardingRules(rules=[("special", (None, "dp"))])
    assert rules.feed_spec_for("special_in", (64, 32), mesh) == \
        (None, "dp")


def test_feed_spec_for_mesh_without_batch_axis():
    mesh = make_mesh({"sp": N_DEV})
    assert ShardingRules().feed_spec_for("x", (64, 32), mesh) == \
        (None, None)


# -- Trainer surface -------------------------------------------------------

def test_trainer_trains_on_dp_mesh_with_int8_sync():
    from paddle_tpu.contrib import Trainer

    def train_func():
        x = layers.data(name="x", shape=[16], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(layers.fc(x, size=64, act="relu"), size=1)
        return layers.mean(layers.square_error_cost(pred, y))

    bs = fluid.BuildStrategy()
    bs.grad_sync = "int8"
    t = Trainer(train_func,
                lambda: fluid.optimizer.SGD(learning_rate=0.05),
                mesh=make_mesh({"dp": N_DEV}), build_strategy=bs)
    assert t.train_program._compiled_wrapper is not None
    assert t.train_program._grad_sync.mode == "int8"

    rng = np.random.RandomState(0)
    losses = []

    def reader():
        for _ in range(4):
            yield {"x": rng.rand(32, 16).astype(np.float32),
                   "y": rng.rand(32, 1).astype(np.float32)}

    t.train(num_epochs=1, reader=reader,
            event_handler=lambda e: losses.append(
                float(np.asarray(e.metrics[0]).reshape(-1)[0]))
            if hasattr(e, "metrics") else None)
    t.stop()
    assert len(losses) == 4 and np.isfinite(losses).all()


# -- bench helpers ---------------------------------------------------------

def test_bench_parse_mesh():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._parse_mesh("dp=8") == {"dp": 8}
    assert bench._parse_mesh("dp=4,mp=2") == {"dp": 4, "mp": 2}
    for bad in ("dp", "dp=0", "=8", "dp=x"):
        with pytest.raises(ValueError):
            bench._parse_mesh(bad)


# -- perf_gate dp schema + regression keys ---------------------------------

def _perf_gate():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _dp_entry(**over):
    e = {"mfu": 0.3, "tokens_per_sec": 1000.0,
         "per_device_tokens_per_sec": 125.0, "mesh": {"dp": 8},
         "n_devices": 8, "grad_sync": None, "comm_bytes": 5.0e8,
         # hybrid-parallel contract (ISSUE 13): every mesh entry
         # carries the sharded step's per-device opt-state bytes
         "opt_state_bytes_per_device": 2.0e8,
         "last_loss": 1.0, "ckpt_blocking_ms": 1.0,
         # numerics observability contract (ISSUE 11): training
         # entries carry the window's grad norm + worst update ratio
         "grad_norm_last": 0.5, "update_ratio_worst": 1e-3,
         # goodput-ledger contract (observe pillar 8): training
         # entries decompose their harness wall next to the headline
         "goodput": 0.9, "effective_mfu": 0.27,
         "badput_breakdown": {"compile": 0.08, "idle": 0.02}}
    e.update(over)
    return e


def test_perf_gate_schema_requires_dp_keys():
    pg = _perf_gate()
    line = {k: 0 for k in pg._SCHEMA_FIELDS}
    line["detail"] = {"transformer_dp8": _dp_entry()}
    assert pg.check_schema(line) == []
    broken = _dp_entry()
    del broken["comm_bytes"], broken["per_device_tokens_per_sec"]
    del broken["opt_state_bytes_per_device"]
    broken["mesh"] = {}
    line["detail"] = {"transformer_dp8": broken}
    errs = pg.check_schema(line)
    assert any("comm_bytes" in e for e in errs)
    assert any("per_device_" in e for e in errs)
    assert any("opt_state_bytes_per_device" in e for e in errs)
    assert any("non-empty axis->size dict" in e for e in errs)


def test_perf_gate_catches_per_device_and_comm_regressions():
    pg = _perf_gate()
    base = {"detail": {"transformer_dp8": _dp_entry()}}
    # 10% per-device throughput drop with aggregate held (mesh grew
    # elsewhere / entry mislabeled) -> caught by the per_device key
    cand = {"detail": {"transformer_dp8": _dp_entry(
        per_device_tokens_per_sec=112.0)}}
    regs, _, compared = pg.gate(base, cand)
    assert compared == 1
    assert any("per_device_tokens_per_sec" in r for r in regs)
    # comm bytes creeping +20% -> regression even at flat throughput
    cand = {"detail": {"transformer_dp8": _dp_entry(
        comm_bytes=6.1e8)}}
    regs, _, _ = pg.gate(base, cand)
    assert any("comm_bytes" in r for r in regs)
    # within tolerance -> clean
    cand = {"detail": {"transformer_dp8": _dp_entry(
        comm_bytes=5.2e8, per_device_tokens_per_sec=120.0)}}
    regs, _, _ = pg.gate(base, cand)
    assert regs == []


def test_perf_gate_never_compares_across_mesh_or_sync_mode():
    pg = _perf_gate()
    base = {"detail": {"transformer_dp8": _dp_entry()}}
    # same entry name, different grad_sync -> reported, not gated
    cand = {"detail": {"transformer_dp8": _dp_entry(
        grad_sync="int8", tokens_per_sec=500.0,
        per_device_tokens_per_sec=62.5)}}
    regs, report, _ = pg.gate(base, cand)
    assert regs == []
    assert any("mesh/grad_sync mismatch" in ln for ln in report)
