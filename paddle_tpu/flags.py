"""Typed runtime flags with env-var bridge.

reference: the gflags system (SURVEY.md §5.6) — ~60 DEFINE_* flags read
from env via python __bootstrap__ (python/paddle/fluid/__init__.py:
125-147).  One typed registry replaces point-of-use globals; env vars
`FLAGS_<name>` override defaults at import, matching the reference's
exposure convention.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict


@dataclass
class _FlagDef:
    name: str
    default: Any
    help: str
    type: type


class FlagRegistry:
    def __init__(self):
        self._defs: Dict[str, _FlagDef] = {}
        self._values: Dict[str, Any] = {}

    def define(self, name: str, default, help_: str = ""):
        t = type(default)
        self._defs[name] = _FlagDef(name, default, help_, t)
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            if t is bool:
                self._values[name] = env.lower() in ("1", "true", "yes")
            else:
                self._values[name] = t(env)
        else:
            self._values[name] = default

    def __getattr__(self, name: str):
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(f"unknown flag {name!r}")

    def __setattr__(self, name: str, value):
        if name in ("_defs", "_values"):
            object.__setattr__(self, name, value)
            return
        if name not in self._defs:
            raise AttributeError(f"unknown flag {name!r}")
        self._values[name] = self._defs[name].type(value)
        if name == "fraction_of_tpu_memory_to_use":
            os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(
                self._values[name])

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)


FLAGS = FlagRegistry()

# Correctness / debugging (reference: operator.cc:943 FLAGS_check_nan_inf,
# §5.2 determinism flags — XLA is deterministic by default on TPU).
FLAGS.define("check_nan_inf", False,
             "scan every fetch for NaN/Inf after each step")
FLAGS.define("benchmark", False,
             "block after every run for accurate timing "
             "(reference operator.cc:940)")
FLAGS.define("cpu_deterministic", True, "kept for parity; XLA/TPU is "
             "deterministic by default")
# Memory (reference: FLAGS_fraction_of_gpu_memory_to_use & allocator
# strategy — XLA owns HBM; preallocation toggles via env)
FLAGS.define("fraction_of_tpu_memory_to_use", 0.9,
             "exported as XLA_PYTHON_CLIENT_MEM_FRACTION; takes effect "
             "only when set before the first device use")


def _export_mem_fraction():
    # reference: FLAGS_fraction_of_gpu_memory_to_use sizes the buddy
    # allocator chunk (memory/allocation/legacy_allocator.cc); on TPU the
    # XLA client owns HBM preallocation, configured via this env var.
    # Exported only when the user explicitly set the flag, so the XLA
    # default stays in effect otherwise.
    os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(
        FLAGS.fraction_of_tpu_memory_to_use)


if "FLAGS_fraction_of_tpu_memory_to_use" in os.environ:
    _export_mem_fraction()
# Executor behavior
FLAGS.define("use_mkldnn", False, "parity no-op (MKLDNN is x86-only)")
FLAGS.define("reader_queue_speed_test_mode", False,
             "non-destructive reader queue for throughput tests")
FLAGS.define("eager_delete_tensor_gb", 0.0,
             "parity no-op; XLA buffer liveness handles eager deletion")


def init_from_env():
    """Re-read FLAGS_* env vars (the reference's __bootstrap__ pass)."""
    for name, d in FLAGS._defs.items():
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            setattr(FLAGS, name,
                    env.lower() in ("1", "true", "yes")
                    if d.type is bool else d.type(env))
