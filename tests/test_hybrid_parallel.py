"""Hybrid-parallel scale-out (ISSUE 13): the fsdp/ZeRO axis, composable
dp×mp / dp×fsdp grad sync, and mesh-shape-agnostic checkpoint
resharding.

Acceptance pins:
- ZeRO memory: per-device resident optimizer-state bytes
  (observe.resident_state_bytes over the SHARDED compile) drop >=1.7x
  at fsdp=2 and scale ~N/1 at fsdp=4/8;
- dp×mp loss parity vs the single-device twin <=1e-5 (the
  test_grad_sync acceptance pattern) with Megatron-sharded params, for
  the implicit GSPMD path AND the explicit bf16 exchange; int8 on the
  composed mesh is bitwise-deterministic and within the documented
  1e-2 of bf16;
- dp×fsdp: the explicit exchange spans BOTH data axes;
- reshard-on-load: a checkpoint saved on a dp=8 mesh loads onto dp=4
  and dp=2×mp=2 with bit-identical LOGICAL params; ZeRO-sharded opt
  state saved at fsdp=8 reassembles bit-identically at fsdp=4 and
  actually lands sharded;
- feed/data-axis spec rules for fsdp meshes.

All models deliberately tiny (8 virtual devices share one host core).
"""

import os

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers, observe
from paddle_tpu.parallel import GradSyncConfig, make_mesh
from paddle_tpu.parallel.strategies import ShardingRules

N_DEV = 8
STEPS = 5


@pytest.fixture(scope="module", autouse=True)
def _need_devices():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")


def _mp_rules():
    # the Megatron pairing: ffn_in column-parallel, ffn_out row-parallel
    return ShardingRules(rules=[
        (r"ffn_in\S*\.w", (None, "mp")),
        (r"ffn_out\S*\.w", ("mp", None)),
    ])


def _build(optimizer="momentum"):
    x = layers.data("x", shape=[32], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, size=128, act="relu", name="ffn_in")
    pred = layers.fc(h, size=1, name="ffn_out")
    loss = layers.mean(layers.square_error_cost(pred, y))
    if optimizer == "adam":
        fluid.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
    else:
        fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9).minimize(loss)
    return loss


def _batches(n=STEPS, b=64, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(b, 32).astype(np.float32),
             "y": rng.randn(b, 1).astype(np.float32)}
            for _ in range(n)]


def _run(mesh_axes, grad_sync=None, rules=None, optimizer="momentum",
         batches=None, want_scope=False):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = _build(optimizer)
        exe = fluid.Executor()
        exe.run(startup)
        if mesh_axes:
            bs = fluid.BuildStrategy()
            bs.grad_sync = grad_sync
            if rules is not None:
                bs.sharding_rules = rules
            fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs,
                mesh=make_mesh(mesh_axes))
        losses = []
        for b in (batches or _batches()):
            (lv,) = exe.run(main, feed=b, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return np.asarray(losses), scope


# -- ZeRO optimizer-state sharding ----------------------------------------

def _opt_bytes(mesh_axes):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = _build("adam")
        exe = fluid.Executor()
        exe.run(startup)
        fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, mesh=make_mesh(mesh_axes))
        feed = _batches(1)[0]
        exe.run(main, feed=feed, fetch_list=[loss])
        rep = observe.sharded_memory_report(
            main, feed=feed, fetch_list=[loss], scope=scope)
    return (observe.resident_state_bytes(rep),
            observe.resident_state_bytes(rep, bucket="params"))


def test_fsdp_opt_state_bytes_drop_and_scale():
    """ACCEPTANCE: per-device resident opt-state bytes drop >=1.7x at
    fsdp=2 vs pure dp, and scale ~N/1 at fsdp=4/8 (the ZeRO claim,
    proven chip-free from the sharded compile's buffer assignment).
    Params stay replicated — ZeRO-1 shards ONLY the accumulators."""
    base, base_params = _opt_bytes({"dp": 2})
    by_n = {}
    for n in (2, 4, 8):
        got, params = _opt_bytes({"fsdp": n})
        by_n[n] = got
        assert params == base_params, (params, base_params)
    assert base / by_n[2] >= 1.7, (base, by_n)
    for n in (4, 8):
        # ~N/1: the big accumulators shard exactly 1/N; only the tiny
        # pow counters/lr stay replicated, so allow 25% slack
        assert base / by_n[n] >= n * 0.75, (n, base, by_n)
    assert by_n[4] < by_n[2] and by_n[8] < by_n[4], by_n


def test_zero_spec_composition():
    """opt_state_spec_for composes the zero axis onto rule specs: an
    mp-sharded accumulator keeps its mp dim and gains fsdp on the
    first free divisible dim; indivisible/scalar state replicates."""
    mesh = make_mesh({"fsdp": 2, "mp": 2})
    rules = _mp_rules()
    assert rules.opt_state_spec_for(
        "ffn_in.w_0.velocity", (32, 128), mesh) == ("fsdp", "mp")
    assert rules.opt_state_spec_for(
        "ffn_in.b_0.velocity", (128,), mesh) == ("fsdp",)
    assert rules.opt_state_spec_for(
        "ffn_in.w_0.beta1_pow_acc", (1,), mesh) == (None,)
    # no fsdp axis in the mesh -> inert
    mesh_dp = make_mesh({"dp": 8})
    assert rules.opt_state_spec_for(
        "ffn_in.b_0.velocity", (128,), mesh_dp) == (None,)


def test_data_axes_and_feed_specs():
    rules = ShardingRules()
    mesh = make_mesh({"dp": 2, "fsdp": 2, "mp": 2})
    assert rules.data_axes_for(mesh, "dp") == ("dp", "fsdp")
    # feed dim0 shards over BOTH data axes when the batch divides
    assert rules.feed_spec_for("x", (8, 4), mesh) == \
        (("dp", "fsdp"), None)
    # divides dp but not dp*fsdp -> dp alone keeps the speedup
    assert rules.feed_spec_for("x", (6, 4), mesh) == ("dp", None)
    # divides nothing -> replicated
    assert rules.feed_spec_for("x", (3, 4), mesh) == (None, None)
    # pure-fsdp mesh: fsdp IS the data axis
    mesh_f = make_mesh({"fsdp": 4})
    assert rules.data_axes_for(mesh_f, "dp") == ("fsdp",)
    assert rules.feed_spec_for("x", (8, 4), mesh_f) == ("fsdp", None)


def test_fsdp_loss_parity_vs_single_device():
    """fsdp=8 (implicit GSPMD, ZeRO opt state) matches the
    single-device twin at a fixed global batch — the dp parity
    acceptance bar extended to the new axis."""
    single, _ = _run(None)
    fsdp, scope = _run({"fsdp": N_DEV})
    np.testing.assert_allclose(fsdp, single, rtol=1e-5, atol=1e-7)
    # and the opt state really is sharded on-device
    vel = next(k for k in scope.vars if k.endswith(".velocity")
               and np.ndim(scope.find_var(k)) == 2)
    v = scope.find_var(vel)
    shapes = {s.data.shape for s in v.addressable_shards}
    assert shapes == {(v.shape[0] // N_DEV, v.shape[1])}, shapes


# -- composable explicit grad sync ----------------------------------------

def test_dpxmp_loss_parity_vs_single_device():
    """ACCEPTANCE: dp=4×mp=2 with Megatron-sharded params — implicit
    GSPMD and the explicit bf16 exchange (partial-auto shard_map over
    dp, mp left to GSPMD) both pin <=1e-5 vs the single-device twin."""
    single, _ = _run(None)
    implicit, _ = _run({"dp": 4, "mp": 2}, rules=_mp_rules())
    np.testing.assert_allclose(implicit, single, rtol=1e-5, atol=1e-7)
    bf16, _ = _run({"dp": 4, "mp": 2}, grad_sync="bf16",
                   rules=_mp_rules())
    np.testing.assert_allclose(bf16, single, rtol=1e-5, atol=1e-7)


def test_dpxmp_int8_deterministic_and_within_tolerance():
    """ACCEPTANCE: int8 grad sync on the composed mesh (psum-form
    exchange) is bitwise-deterministic run-to-run, actually quantizes,
    descends, and stays within the documented 1e-2 of the bf16 control
    arm."""
    cfg = GradSyncConfig("int8", min_quant_numel=1)
    a, _ = _run({"dp": 4, "mp": 2}, grad_sync=cfg, rules=_mp_rules())
    b, _ = _run({"dp": 4, "mp": 2}, grad_sync=cfg, rules=_mp_rules())
    assert np.array_equal(a, b), "int8 on dp×mp not deterministic"
    bf16, _ = _run({"dp": 4, "mp": 2}, grad_sync="bf16",
                   rules=_mp_rules())
    assert not np.array_equal(a, bf16), \
        "quantization inactive — the A/B would compare the exchange " \
        "to itself"
    rel = np.abs(a - bf16) / np.maximum(np.abs(bf16), 1e-6)
    assert rel.max() < 1e-2, rel
    assert np.isfinite(a).all()


def test_dpxfsdp_explicit_sync_spans_both_axes():
    """dp=4×fsdp=2: the explicit exchange maps over BOTH data axes
    (bf16 parity vs single device) and int8 rides the psum-form
    exchange deterministically."""
    single, _ = _run(None)
    bf16, _ = _run({"dp": 4, "fsdp": 2}, grad_sync="bf16")
    np.testing.assert_allclose(bf16, single, rtol=1e-5, atol=1e-7)
    cfg = GradSyncConfig("int8", min_quant_numel=1)
    a, _ = _run({"dp": 4, "fsdp": 2}, grad_sync=cfg)
    b, _ = _run({"dp": 4, "fsdp": 2}, grad_sync=cfg)
    assert np.array_equal(a, b)
    rel = np.abs(a - bf16) / np.maximum(np.abs(bf16), 1e-6)
    assert rel.max() < 1e-2, rel


def test_quantized_all_reduce_psum_matches_wire_form():
    """The psum-form exchange is the SAME quantization scheme as the
    wire (all_to_all/all_gather) form: on identical per-rank inputs
    the two produce results within the analytic error bound of each
    other, and the psum form is deterministic and replicated-bitwise
    across ranks."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.collectives import (
        compat_shard_map, quantized_all_reduce_local,
        quantized_all_reduce_psum)

    mesh = make_mesh({"dp": N_DEV})
    rng = np.random.RandomState(0)
    x = rng.randn(N_DEV, 70000).astype(np.float32)

    def wire(xs):
        return quantized_all_reduce_local(
            xs.reshape(-1), "dp", N_DEV, op="mean").reshape(1, -1)

    def psum_form(xs):
        return quantized_all_reduce_psum(
            xs.reshape(-1), "dp", N_DEV, None, op="mean"
        ).reshape(1, -1)

    got_wire = np.asarray(compat_shard_map(
        wire, mesh, (P("dp", None),), P("dp", None))(jnp.asarray(x)))
    got_psum = np.asarray(compat_shard_map(
        psum_form, mesh, (P("dp", None),), P("dp", None))(
            jnp.asarray(x)))
    exact = x.mean(0)
    # every rank's copy is identical (replicated-bitwise)
    assert all(np.array_equal(got_psum[0], got_psum[i])
               for i in range(N_DEV))
    # both forms sit within the documented elementwise bound of exact
    for got in (got_wire[0], got_psum[0]):
        rel = np.abs(got - exact).max() / np.abs(exact).max()
        assert rel < 0.05, rel
    # and the two forms agree with each other far tighter than the
    # bound (same quantize/dequantize; only the sum order differs)
    rel = np.abs(got_wire[0] - got_psum[0]).max() / np.abs(exact).max()
    assert rel < 0.05, rel


def test_sparse_grads_stay_sparse_on_composed_mesh(monkeypatch):
    """The SparseGrad path on a dp×fsdp mesh: the table grad rides the
    psum-concat gather (never the quantized dense exchange) and
    untouched rows stay bit-identical."""
    from paddle_tpu.parallel import collectives

    V, D, B, F = 64, 16, 32, 4

    def build_sparse():
        ids = layers.data("ids", shape=[B, F], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        emb = layers.embedding(
            ids, size=[V, D], is_sparse=True,
            param_attr=fluid.ParamAttr(
                name="tbl",
                initializer=fluid.initializer.Constant(0.05)))
        s = layers.reduce_sum(emb, dim=1)
        h = layers.fc(s, size=256, act="relu")
        p = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return loss

    seen = []
    real = collectives.quantized_all_reduce_psum

    def spy(g, *a, **kw):
        seen.append(tuple(g.shape))
        return real(g, *a, **kw)

    monkeypatch.setattr(collectives, "quantized_all_reduce_psum", spy)

    rng = np.random.RandomState(1)
    batches = [{"ids": rng.randint(0, V // 2, (B, F)).astype(np.int64),
                "y": rng.rand(B, 1).astype(np.float32)}
               for _ in range(3)]

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = build_sparse()
        exe = fluid.Executor()
        exe.run(startup)
        bs = fluid.BuildStrategy()
        bs.grad_sync = GradSyncConfig("int8", min_quant_numel=1)
        fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs,
            mesh=make_mesh({"dp": 4, "fsdp": 2}))
        losses = []
        for b in batches:
            (lv,) = exe.run(main, feed=b, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    assert (V, D) not in seen, seen          # table never densified
    assert any(len(s) == 2 and s[0] * s[1] >= 256 for s in seen), seen
    table = np.asarray(scope.find_var("tbl"))
    np.testing.assert_array_equal(
        table[V // 2:], np.full((V - V // 2, D), 0.05, np.float32))


# -- mesh-shape-agnostic reshard on load ----------------------------------

def _train_and_save(mesh_axes, ckpt, steps=2, rules=None,
                    optimizer="momentum"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = _build(optimizer)
        exe = fluid.Executor()
        exe.run(startup)
        bs = fluid.BuildStrategy()
        if rules is not None:
            bs.sharding_rules = rules
        fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs,
            mesh=make_mesh(mesh_axes))
        for b in _batches(steps):
            exe.run(main, feed=b, fetch_list=[loss])
        fluid.io.save_sharded(exe, ckpt, main_program=main)
        vals = {v.name: np.asarray(scope.find_var(v.name))
                for v in main.list_vars() if v.persistable}
    return vals


def _load_on(mesh_axes, ckpt, rules=None, optimizer="momentum"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = _build(optimizer)
        exe = fluid.Executor()
        exe.run(startup)
        bs = fluid.BuildStrategy()
        if rules is not None:
            bs.sharding_rules = rules
        fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs,
            mesh=make_mesh(mesh_axes))
        fluid.io.load_sharded(exe, ckpt, main_program=main,
                              mesh=make_mesh(mesh_axes))
        vals = {v.name: np.asarray(scope.find_var(v.name))
                for v in main.list_vars() if v.persistable}
        # per-device shard shapes BEFORE the step (the step donates
        # and consumes the loaded arrays)
        shard_shapes = {
            v.name: {s.data.shape
                     for s in scope.find_var(v.name).addressable_shards}
            for v in main.list_vars() if v.persistable
            if hasattr(scope.find_var(v.name), "addressable_shards")}
        # the loaded state still trains (one step proves the shardings
        # entered the executable coherently)
        (lv,) = exe.run(main, feed=_batches(1, seed=9)[0],
                        fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv).reshape(-1)[0]))
    return vals, shard_shapes


def test_reshard_dp8_to_dp4_and_dp2mp2(tmp_path):
    """ACCEPTANCE: a dp=8-saved checkpoint loads onto dp=4 and
    dp=2×mp=2 meshes with bit-identical LOGICAL params — the missing
    half of gang elasticity."""
    ckpt = str(tmp_path / "ck_dp8")
    saved = _train_and_save({"dp": 8}, ckpt)
    for axes, rules in (({"dp": 4}, None),
                        ({"dp": 2, "mp": 2}, _mp_rules())):
        got, shard_shapes = _load_on(axes, ckpt, rules=rules)
        for name, want in saved.items():
            np.testing.assert_array_equal(
                got[name], want,
                err_msg=f"{name} not bit-identical on {axes}")
    # and on the dp2mp2 mesh the mp-sharded fc really landed SHARDED
    # (shard_shapes is from the dp2mp2 iteration above)
    w = next(n for n in shard_shapes if "ffn_in" in n and ".w_" in n
             and not n.split(".w_0")[-1])
    assert shard_shapes[w] == {(32, 64)}, \
        (w, shard_shapes[w])  # (32,128) split over mp=2


def test_reshard_zero_opt_state_fsdp8_to_fsdp4(tmp_path):
    """ZeRO-sharded optimizer state saved at fsdp=8 reassembles
    bit-identically at fsdp=4 AND lands 1/4-sharded (the
    state_spec_for composition on load) — a shrunken gang resumes with
    its opt-state memory win intact."""
    ckpt = str(tmp_path / "ck_fsdp8")
    saved = _train_and_save({"fsdp": 8}, ckpt, optimizer="adam")
    got, shard_shapes = _load_on({"fsdp": 4}, ckpt, optimizer="adam")
    for name, want in saved.items():
        np.testing.assert_array_equal(got[name], want, err_msg=name)
    mom = next(n for n in shard_shapes if n.endswith(".moment1")
               and saved[n].ndim == 2)
    d0, d1 = saved[mom].shape
    assert shard_shapes[mom] == {(d0 // 4, d1)}, \
        (mom, shard_shapes[mom])


def test_reshard_to_single_device(tmp_path):
    """The degenerate reshard: a dp=8-sharded save loads host-side
    (mesh=None) bit-identically — the manifest's global indices are
    the only source of truth."""
    ckpt = str(tmp_path / "ck_dp8s")
    saved = _train_and_save({"fsdp": 8}, ckpt, optimizer="adam")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        _build("adam")
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.load_sharded(exe, ckpt, main_program=main)
        for name, want in saved.items():
            got = np.asarray(scope.find_var(name))
            np.testing.assert_array_equal(got, want, err_msg=name)
