"""Straggler-op sweep (VERDICT round-2 item 10): lstmp, mean_iou,
psroi_pool, random_crop, conv_shift, lod_reset, modified_huber_loss,
similarity_focus, positive_negative_pair.

Each op's numeric check mirrors the reference kernel semantics
(reference file cited per test); reference numbers are recomputed here
in plain numpy, never copied.
"""

from __future__ import annotations

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers

from op_test import check_grad, check_output, run_op


# -- conv_shift (reference conv_shift_op.cc) --------------------------------

def _conv_shift_np(x, y):
    b, m = x.shape
    n = y.shape[1]
    half = (n - 1) // 2
    o = np.zeros_like(x)
    for i in range(m):
        for j in range(n):
            o[:, i] += x[:, (i + j - half + m) % m] * y[:, j]
    return o


def test_conv_shift_forward():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 17).astype(np.float32)
    y = rng.rand(4, 3).astype(np.float32)
    check_output("conv_shift", {"X": x, "Y": y}, _conv_shift_np(x, y),
                 rtol=1e-5)


def test_conv_shift_grad():
    rng = np.random.RandomState(1)
    x = rng.rand(2, 9).astype(np.float32)
    y = rng.rand(2, 3).astype(np.float32)
    check_grad("conv_shift", {"X": x, "Y": y}, "X")
    check_grad("conv_shift", {"X": x, "Y": y}, "Y")


# -- modified_huber_loss (reference modified_huber_loss_op.cc) --------------

def test_modified_huber_loss():
    rng = np.random.RandomState(2)
    x = rng.randn(8, 1).astype(np.float32)
    y = rng.randint(0, 2, (8, 1)).astype(np.float32)
    yf = (2 * y - 1) * x
    expected = np.where(yf >= -1, np.maximum(0, 1 - yf) ** 2, -4 * yf)
    check_output("modified_huber_loss", {"X": x, "Y": y}, expected)
    # keep x away from the yf == -1 kink for finite differences
    x2 = np.where(np.abs((2 * y - 1) * x + 1) < 0.05, x + 0.2, x)
    check_grad("modified_huber_loss", {"X": x2, "Y": y}, "X")


# -- mean_iou (reference mean_iou_op.h) -------------------------------------

def _mean_iou_np(pred, label, n_cls):
    wrong = np.zeros(n_cls, np.int32)
    correct = np.zeros(n_cls, np.int32)
    for p, l in zip(pred.ravel(), label.ravel()):
        if p == l:
            correct[p] += 1
        else:
            wrong[l] += 1
            wrong[p] += 1
    denom = wrong + correct
    valid = denom > 0
    iou = np.where(valid, correct / np.maximum(denom, 1), 0.0)
    return iou.sum() / max(valid.sum(), 1), wrong, correct


def test_mean_iou():
    rng = np.random.RandomState(3)
    pred = rng.randint(0, 5, (4, 16)).astype(np.int32)
    label = rng.randint(0, 5, (4, 16)).astype(np.int32)
    miou, wrong, correct = _mean_iou_np(pred, label, 5)
    got_miou, got_wrong, got_correct = (
        run_op("mean_iou", {"Predictions": pred, "Labels": label},
               {"num_classes": 5}, out_slot="OutMeanIou"),
        run_op("mean_iou", {"Predictions": pred, "Labels": label},
               {"num_classes": 5}, out_slot="OutWrong"),
        run_op("mean_iou", {"Predictions": pred, "Labels": label},
               {"num_classes": 5}, out_slot="OutCorrect"),
    )
    np.testing.assert_allclose(got_miou, [miou], rtol=1e-6)
    np.testing.assert_array_equal(got_wrong, wrong)
    np.testing.assert_array_equal(got_correct, correct)


def test_mean_iou_accumulates():
    pred = np.array([[0, 1]], np.int32)
    label = np.array([[0, 1]], np.int32)
    prev_w = np.array([1, 0, 0], np.int32)
    prev_c = np.array([0, 2, 0], np.int32)
    wrong = run_op("mean_iou",
                   {"Predictions": pred, "Labels": label,
                    "InWrongs": [prev_w], "InCorrects": [prev_c]},
                   {"num_classes": 3}, out_slot="OutWrong")
    correct = run_op("mean_iou",
                     {"Predictions": pred, "Labels": label,
                      "InWrongs": [prev_w], "InCorrects": [prev_c]},
                     {"num_classes": 3}, out_slot="OutCorrect")
    np.testing.assert_array_equal(wrong, [1, 0, 0])
    np.testing.assert_array_equal(correct, [1, 3, 0])


# -- positive_negative_pair (reference positive_negative_pair_op.cc) --------

def _pnpair_np(score, label, query, column=-1, weight=None):
    n = label.shape[0]
    if weight is None:
        weight = np.ones((n, 1), np.float32)
    groups = {}
    for s, l, q, w in zip(score, label, query, weight):
        groups.setdefault(q[0], []).append((s[column], l[0], w[0]))
    pos = neg = neu = 0.0
    for ranks in groups.values():
        for e1, e2 in itertools.combinations(ranks, 2):
            (s1, l1, w1), (s2, l2, w2) = e1, e2
            if l1 == l2:
                continue
            w = (w1 + w2) * 0.5
            if s1 == s2:
                neu += w
            elif (s1 - s2) * (l1 - l2) > 0:
                pos += w
            else:
                neg += w
    return pos, neg, neu


def test_positive_negative_pair():
    rng = np.random.RandomState(4)
    n = 20
    score = rng.randn(n, 3).astype(np.float32)
    label = rng.randint(0, 3, (n, 1)).astype(np.float32)
    query = rng.randint(0, 4, (n, 1)).astype(np.int64)
    pos, neg, neu = _pnpair_np(score, label, query, column=1)
    ins = {"Score": score, "Label": label, "QueryID": query}
    got_p = run_op("positive_negative_pair", ins, {"column": 1},
                   out_slot="PositivePair")
    got_n = run_op("positive_negative_pair", ins, {"column": 1},
                   out_slot="NegativePair")
    got_u = run_op("positive_negative_pair", ins, {"column": 1},
                   out_slot="NeutralPair")
    np.testing.assert_allclose(got_p, [pos], rtol=1e-5)
    np.testing.assert_allclose(got_n, [neg], rtol=1e-5)
    np.testing.assert_allclose(got_u, [neu], rtol=1e-5)


def test_positive_negative_pair_weighted_accum():
    rng = np.random.RandomState(5)
    n = 12
    score = rng.randn(n, 1).astype(np.float32)
    label = rng.randint(0, 2, (n, 1)).astype(np.float32)
    query = rng.randint(0, 2, (n, 1)).astype(np.int64)
    weight = rng.rand(n, 1).astype(np.float32)
    pos, _, _ = _pnpair_np(score, label, query, weight=weight)
    got = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": query,
                  "Weight": weight,
                  "AccumulatePositivePair": np.array([2.5], np.float32)},
                 {"column": -1}, out_slot="PositivePair")
    np.testing.assert_allclose(got, [pos + 2.5], rtol=1e-5)


# -- lstmp (reference lstmp_op.cc) ------------------------------------------

def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def test_lstmp_matches_numpy_recurrence():
    rng = np.random.RandomState(6)
    n, t, d, p = 2, 5, 4, 3
    x = rng.randn(n, t, 4 * d).astype(np.float32) * 0.5
    w = rng.randn(p, 4 * d).astype(np.float32) * 0.3
    w_proj = rng.randn(d, p).astype(np.float32) * 0.3
    bias = rng.randn(1, 4 * d).astype(np.float32) * 0.1

    r = np.zeros((n, p), np.float32)
    c = np.zeros((n, d), np.float32)
    rs = []
    for step in range(t):
        gates = x[:, step] + bias.reshape(-1) + r @ w
        cand, i, f, o = np.split(gates, 4, axis=-1)
        i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
        c = f * c + i * np.tanh(cand)
        h = o * np.tanh(c)
        r = np.tanh(h @ w_proj)
        rs.append(r.copy())
    expected = np.stack(rs, axis=1)

    got = run_op("lstmp",
                 {"Input": x, "Weight": w, "ProjWeight": w_proj,
                  "Bias": bias},
                 {"use_peepholes": False}, out_slot="Projection")
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


def test_lstmp_grad_and_masking():
    rng = np.random.RandomState(7)
    n, t, d, p = 2, 4, 3, 2
    x = rng.randn(n, t, 4 * d).astype(np.float32) * 0.3
    w = rng.randn(p, 4 * d).astype(np.float32) * 0.3
    w_proj = rng.randn(d, p).astype(np.float32) * 0.3
    seq_len = np.array([4, 2], np.int32)
    proj = run_op("lstmp",
                  {"Input": x, "Weight": w, "ProjWeight": w_proj,
                   "SeqLen": seq_len},
                  {"use_peepholes": False}, out_slot="Projection")
    # past-end steps freeze the state
    np.testing.assert_allclose(proj[1, 2], proj[1, 1], rtol=1e-6)
    np.testing.assert_allclose(proj[1, 3], proj[1, 1], rtol=1e-6)
    check_grad("lstmp",
               {"Input": x, "Weight": w, "ProjWeight": w_proj},
               "Weight", {"use_peepholes": False}, out_slot="Projection",
               max_relative_error=2e-2)


def test_dynamic_lstmp_layer_builds_and_runs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        data = layers.data(name="x", shape=[6, 8], dtype="float32")
        proj, cell = layers.dynamic_lstmp(data, size=8, proj_size=3)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.random.RandomState(8).randn(2, 6, 8).astype(np.float32)
        pv, cv = exe.run(main, feed={"x": xv}, fetch_list=[proj, cell])
    assert pv.shape == (2, 6, 3)
    assert cv.shape == (2, 6, 2)


# -- psroi_pool (reference psroi_pool_op.h) ---------------------------------

def _psroi_np(x, rois, c_out, ph, pw, scale):
    _n, c_in, h, w = x.shape
    out_arr = np.zeros((rois.shape[0], c_out, ph, pw), np.float32)
    for ri, roi in enumerate(rois):
        bi = int(roi[0])
        x1 = round(roi[1]) * scale
        y1 = round(roi[2]) * scale
        x2 = (round(roi[3]) + 1.0) * scale
        y2 = (round(roi[4]) + 1.0) * scale
        rh = max(y2 - y1, 0.1)
        rw = max(x2 - x1, 0.1)
        bh, bw = rh / ph, rw / pw
        for c in range(c_out):
            for i in range(ph):
                for j in range(pw):
                    hs = min(max(int(np.floor(i * bh + y1)), 0), h)
                    he = min(max(int(np.ceil((i + 1) * bh + y1)), 0), h)
                    ws = min(max(int(np.floor(j * bw + x1)), 0), w)
                    we = min(max(int(np.ceil((j + 1) * bw + x1)), 0), w)
                    cin = (c * ph + i) * pw + j
                    if he <= hs or we <= ws:
                        continue
                    region = x[bi, cin, hs:he, ws:we]
                    out_arr[ri, c, i, j] = region.sum() / (
                        (he - hs) * (we - ws))
    return out_arr


def test_psroi_pool():
    rng = np.random.RandomState(9)
    c_out, ph, pw = 2, 2, 2
    x = rng.rand(2, c_out * ph * pw, 8, 8).astype(np.float32)
    rois = np.array([
        [0, 1, 1, 6, 6],
        [1, 0, 2, 7, 5],
        [0, 3, 3, 3, 3],
    ], np.float32)
    expected = _psroi_np(x, rois, c_out, ph, pw, 1.0)
    check_output("psroi_pool", {"X": x, "ROIs": rois}, expected,
                 {"output_channels": c_out, "pooled_height": ph,
                  "pooled_width": pw, "spatial_scale": 1.0}, rtol=1e-4,
                 atol=1e-5)


def test_psroi_pool_grad():
    rng = np.random.RandomState(10)
    x = rng.rand(1, 8, 6, 6).astype(np.float32)
    rois = np.array([[0, 0, 0, 5, 5]], np.float32)
    check_grad("psroi_pool", {"X": x, "ROIs": rois}, "X",
               {"output_channels": 2, "pooled_height": 2,
                "pooled_width": 2, "spatial_scale": 1.0},
               max_relative_error=1e-2)


# -- random_crop (reference random_crop_op.cc) ------------------------------

def test_random_crop_shape_and_content():
    rng = np.random.RandomState(11)
    x = rng.rand(4, 3, 10, 10).astype(np.float32)
    o = run_op("random_crop", {"X": x}, {"shape": [3, 6, 6]})
    assert o.shape == (4, 3, 6, 6)
    # every crop window must be a contiguous sub-block of its instance
    for i in range(4):
        found = False
        for dy in range(5):
            for dx in range(5):
                if np.allclose(o[i], x[i, :, dy:dy + 6, dx:dx + 6]):
                    found = True
        assert found, f"crop {i} is not a sub-block of instance {i}"


# -- lod_reset (reference lod_reset_op.cc) ----------------------------------

def test_lod_reset_plain_rows():
    rng = np.random.RandomState(12)
    x = rng.rand(6, 3).astype(np.float32)
    o = run_op("lod_reset", {"X": x}, {"target_lod": [0, 2, 6]})
    lens = run_op("lod_reset", {"X": x}, {"target_lod": [0, 2, 6]},
                  out_slot="Length")
    assert o.shape == (2, 4, 3)
    np.testing.assert_array_equal(lens, [2, 4])
    np.testing.assert_allclose(o[0, :2], x[:2])
    np.testing.assert_allclose(o[1, :4], x[2:])
    np.testing.assert_allclose(o[0, 2:], 0)


def test_lod_reset_from_padded_sequences():
    rng = np.random.RandomState(13)
    x = rng.rand(3, 4, 2).astype(np.float32)   # padded, lens [2, 4, 1]
    seq_len = np.array([2, 4, 1], np.int32)
    # stream = x[0,:2] + x[1,:4] + x[2,:1] (7 tokens) → re-split [3, 4]
    o = run_op("lod_reset", {"X": x, "SeqLen": seq_len},
               {"target_lod": [0, 3, 7]})
    stream = np.concatenate([x[0, :2], x[1, :4], x[2, :1]])
    np.testing.assert_allclose(o[0, :3], stream[:3])
    np.testing.assert_allclose(o[1, :4], stream[3:])


def test_lod_reset_layer_attaches_companion():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[3], dtype="float32")
        o = layers.lod_reset(x, y=[2, 2])
        assert layers.seq_len_var(o) is not None
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.arange(12, dtype=np.float32).reshape(4, 3)
        ov, = exe.run(main, feed={"x": xv}, fetch_list=[o])
    assert ov.shape == (2, 2, 3)
    np.testing.assert_allclose(ov.reshape(4, 3), xv)


# -- similarity_focus (reference similarity_focus_op.h) ---------------------

def _similarity_focus_np(x, axis, indexes):
    b = x.shape[0]
    dims = x.shape
    o = np.zeros_like(x)
    for i in range(b):
        for index in indexes:
            if axis == 1:
                t = x[i, index, :, :]
                r_dim, c_dim = dims[2], dims[3]
            elif axis == 2:
                t = x[i, :, index, :]
                r_dim, c_dim = dims[1], dims[3]
            else:
                t = x[i, :, :, index]
                r_dim, c_dim = dims[1], dims[2]
            order = np.argsort(-t.ravel(), kind="stable")
            tag_r = np.zeros(r_dim, bool)
            tag_c = np.zeros(c_dim, bool)
            picked = 0
            for flat in order:
                ri, ci = divmod(int(flat), c_dim)
                if tag_r[ri] or tag_c[ci]:
                    continue
                tag_r[ri] = tag_c[ci] = True
                picked += 1
                if axis == 1:
                    o[i, :, ri, ci] = 1
                elif axis == 2:
                    o[i, ri, :, ci] = 1
                else:
                    o[i, ri, ci, :] = 1
                if picked == min(r_dim, c_dim):
                    break
    return o


@pytest.mark.parametrize("axis", [1, 2, 3])
def test_similarity_focus(axis):
    rng = np.random.RandomState(14)
    x = rng.rand(2, 3, 4, 5).astype(np.float32)
    expected = _similarity_focus_np(x, axis, [0, 1])
    check_output("similarity_focus", {"X": x}, expected,
                 {"axis": axis, "indexes": [0, 1]})


# -- layer wrappers smoke ----------------------------------------------------

def test_straggler_layer_wrappers_build():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    rng = np.random.RandomState(15)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        seg = layers.data(name="seg", shape=[16], dtype="int32")
        lbl = layers.data(name="lbl", shape=[16], dtype="int32")
        miou, _, _ = layers.mean_iou(seg, lbl, num_classes=4)
        img = layers.data(name="img", shape=[8, 6, 6], dtype="float32")
        rois = layers.data(name="rois", shape=[5], dtype="float32")
        pooled = layers.psroi_pool(img, rois, output_channels=2,
                                   spatial_scale=1.0, pooled_height=2,
                                   pooled_width=2)
        cropped = layers.random_crop(img, shape=[8, 4, 4])
        flat = layers.reshape(img, shape=[0, 8 * 36])
        cs = layers.conv_shift(
            layers.slice(flat, axes=[1], starts=[0], ends=[9]),
            layers.slice(flat, axes=[1], starts=[0], ends=[3]))
        score = layers.data(name="score", shape=[1], dtype="float32")
        ylab = layers.data(name="ylab", shape=[1], dtype="float32")
        mh = layers.modified_huber_loss(score, ylab)
        qid = layers.data(name="qid", shape=[1], dtype="int64")
        pos, neg, neu = layers.positive_negative_pair(score, ylab, qid)
        sf = layers.similarity_focus(img, axis=1, indexes=[0])
        exe = fluid.Executor()
        exe.run(startup)
        feeds = {
            "seg": rng.randint(0, 4, (2, 16)).astype(np.int32),
            "lbl": rng.randint(0, 4, (2, 16)).astype(np.int32),
            "img": rng.rand(2, 8, 6, 6).astype(np.float32),
            "rois": np.array([[0, 0, 0, 5, 5]], np.float32),
            "score": rng.randn(6, 1).astype(np.float32),
            "ylab": rng.randint(0, 2, (6, 1)).astype(np.float32),
            "qid": rng.randint(0, 2, (6, 1)).astype(np.int64),
        }
        vals = exe.run(main, feed=feeds,
                       fetch_list=[miou, pooled, cropped, cs, mh, pos,
                                   neg, neu, sf])
    assert vals[1].shape == (1, 2, 2, 2)
    assert vals[2].shape == (2, 8, 4, 4)
    assert vals[8].shape == (2, 8, 6, 6)


# -- LoD-2 sequence family (round-3: VERDICT item 9) -------------------------

def test_sequence_concat_packs_ragged_level1():
    """Corresponding sequences pack back-to-back (reference
    sequence_concat_op), not padded time-axis concat."""
    x1 = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    x2 = 100 + np.arange(8, dtype=np.float32).reshape(2, 2, 2)
    l1 = np.array([3, 1], np.int32)
    l2 = np.array([1, 2], np.int32)
    o = run_op("sequence_concat",
               {"X": [x1, x2], "SeqLen": [l1, l2]}, {})
    lens = run_op("sequence_concat",
                  {"X": [x1, x2], "SeqLen": [l1, l2]}, {},
                  out_slot="Length")
    np.testing.assert_array_equal(lens, [4, 3])
    np.testing.assert_allclose(o[0, :4], np.concatenate([x1[0, :3],
                                                         x2[0, :1]]))
    np.testing.assert_allclose(o[1, :3], np.concatenate([x1[1, :1],
                                                         x2[1, :2]]))
    np.testing.assert_allclose(o[0, 4:], 0)


def test_sequence_concat_nested_level2():
    """Nested inputs concat along the sub-sequence axis with merged
    companions (reference lod_tensor.h multi-level append)."""
    x1 = np.arange(24, dtype=np.float32).reshape(2, 2, 3, 2)
    x2 = 100 + np.arange(16, dtype=np.float32).reshape(2, 2, 2, 2)
    l1 = np.array([2, 1], np.int32)       # sub-sequence counts
    l2 = np.array([1, 2], np.int32)
    l1_2 = np.array([[3, 2], [1, 0]], np.int32)   # inner lengths
    l2_2 = np.array([[2, 0], [1, 2]], np.int32)
    ins = {"X": [x1, x2], "SeqLen": [l1, l2], "SeqLen2": [l1_2, l2_2]}
    o = run_op("sequence_concat", ins, {})
    lens = run_op("sequence_concat", ins, {}, out_slot="Length")
    lens2 = run_op("sequence_concat", ins, {}, out_slot="Length2")
    np.testing.assert_array_equal(lens, [3, 3])
    assert o.shape == (2, 4, 3, 2)        # S1 total 4, S2 max 3
    # row 0: subseqs [x1[0,0], x1[0,1], x2[0,0]]
    np.testing.assert_allclose(o[0, 0], x1[0, 0])
    np.testing.assert_allclose(o[0, 1], x1[0, 1])
    np.testing.assert_allclose(o[0, 2, :2], x2[0, 0])
    np.testing.assert_array_equal(lens2[0, :3], [3, 2, 2])
    # row 1: subseqs [x1[1,0], x2[1,0], x2[1,1]]
    np.testing.assert_allclose(o[1, 0], x1[1, 0])
    np.testing.assert_allclose(o[1, 1, :2], x2[1, 0])
    np.testing.assert_array_equal(lens2[1, :3], [1, 1, 2])


def test_sequence_expand_nested_y():
    """X sequences broadcast across a nested Y's sub-sequence slots;
    the output is itself nested (reference sequence_expand_op.h
    ref_level=0, 2-level Y)."""
    x = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    x_len = np.array([3, 2], np.int32)
    y = np.zeros((2, 4, 5, 1), np.float32)
    y_len = np.array([4, 2], np.int32)
    y_len2 = np.array([[5, 3, 2, 1], [4, 2, 0, 0]], np.int32)
    ins = {"X": [x], "Y": [y], "SeqLen": [x_len], "YLen": [y_len],
           "YLen2": [y_len2]}
    o = run_op("sequence_expand", ins, {})
    outer = run_op("sequence_expand", ins, {}, out_slot="Length")
    inner = run_op("sequence_expand", ins, {}, out_slot="Length2")
    assert o.shape == (2, 4, 3, 2)
    np.testing.assert_array_equal(outer, [4, 2])
    np.testing.assert_array_equal(inner, [[3, 3, 3, 3], [2, 2, 0, 0]])
    for s in range(4):
        np.testing.assert_allclose(o[0, s], x[0])


def test_nested_expand_then_pool_roundtrip_in_graph():
    """Layer-level: expand by nested y → nested output consumable by
    sequence_pool (the one nested-aware reducer), closing the loop
    data(lod_level=2) → expand → pool."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[3, 2], dtype="float32",
                        lod_level=1)
        yv = layers.data(name="yv", shape=[4, 5, 1], dtype="float32",
                         lod_level=2)
        expanded = layers.sequence_expand(x, yv)
        assert layers.seq_len_var(expanded) is not None
        assert layers.seq_len2_var(expanded) is not None
        pooled = layers.sequence_pool(expanded, "sum")
        exe = fluid.Executor()
        exe.run(startup)
        feed = {
            "x": np.ones((2, 3, 2), np.float32),
            "x.seq_len": np.array([3, 2], np.int32),
            "yv": np.zeros((2, 4, 5, 1), np.float32),
            "yv.seq_len": np.array([4, 2], np.int32),
            "yv.seq_len2": np.array([[5, 3, 2, 1], [4, 2, 0, 0]],
                                    np.int32),
        }
        pv, = exe.run(main, feed=feed, fetch_list=[pooled])
    # pooling the inner level of (N, S1, Tx, D) sums over Tx... the
    # nested pool consumes (B, S1, S2, D) with seq_len2 as inner lens:
    # here inner lens are x's lengths broadcast per slot
    assert pv.shape == (2, 4, 2)
    np.testing.assert_allclose(pv[0, 0], [3.0, 3.0])
    np.testing.assert_allclose(pv[1, 0], [2.0, 2.0])


# ---------------------------------------------------------------------------
# round-4 op tail regressions (code-review findings)
# ---------------------------------------------------------------------------

def test_attention_lstm_zero_length_row_finite_grads():
    """A seq_len==0 row must not NaN the weight grads: the attention
    softmax masks with a finite -1e30 (not -inf) and zeroes p, so the
    empty row contributes nothing anywhere."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.registry import OpContext, get_op_impl

    impl = get_op_impl("attention_lstm")
    rng = np.random.RandomState(0)
    n, t, m, d = 2, 3, 4, 2
    x = jnp.asarray(rng.randn(n, t, m), jnp.float32)
    c0 = jnp.asarray(rng.randn(n, d), jnp.float32)
    aw = jnp.asarray(rng.randn(m + d, 1), jnp.float32)
    lw = jnp.asarray(rng.randn(d + m, 4 * d) * 0.3, jnp.float32)
    lb = jnp.zeros((1, 4 * d), jnp.float32)
    seq = jnp.asarray([2, 0], jnp.int32)  # second row EMPTY

    def loss(lw_, aw_, x_):
        outs = impl(OpContext(jax.random.PRNGKey(0), 0),
                    {"X": [x_], "C0": [c0], "AttentionWeight": [aw_],
                     "LSTMWeight": [lw_], "LSTMBias": [lb],
                     "SeqLen": [seq]}, {})
        return jnp.sum(outs["Hidden"][0])

    g_lw, g_aw, g_x = jax.grad(loss, argnums=(0, 1, 2))(lw, aw, x)
    for g in (g_lw, g_aw, g_x):
        assert np.isfinite(np.asarray(g)).all(), "NaN grad from empty row"
    # the empty row's inputs get exactly zero gradient
    np.testing.assert_allclose(np.asarray(g_x)[1], 0.0)


def test_teacher_student_sigmoid_loss_label_boundaries():
    """Branch boundaries match the public op (label <-1 / <0 / <1 /
    else): label==1.0 is clk=1 with teacher score 0."""
    from tests.op_test import run_op

    x = np.array([[0.3], [0.3], [0.3], [0.3]], np.float32)
    lbl = np.array([[-2.0], [-1.0], [0.0], [1.0]], np.float32)
    y = run_op("teacher_student_sigmoid_loss", {"X": x, "Label": lbl},
               out_slot="Y")

    def bce(z, t):
        return max(z, 0) - z * t + np.log1p(np.exp(-abs(z)))

    z = 0.3
    expect = [bce(z, 0),                 # -2: clk0, no teacher
              bce(z, 1),                 # -1: clk1, no teacher
              bce(z, 0) + bce(z, 0.0),   # 0: clk0, teacher 0
              bce(z, 1) + bce(z, 0.0)]   # 1: clk1, teacher 1-1=0
    np.testing.assert_allclose(y.reshape(-1), expect, rtol=1e-5)
