"""collectives.quantized_all_reduce: the EQuARX-style blockwise-int8
gradient exchange (ISSUE 10, docs/DIST.md).

What these tests pin:
- the ERROR MODEL: elementwise |quantized - exact| is bounded by the
  analytic two-phase bound (0.5·Σ_r s_r phase-1 rounding + 0.5·s₂
  phase-2 rounding, s = per-block max/127) — the bound documented in
  docs/DIST.md, asserted, not vibes;
- BITWISE determinism: two invocations agree exactly (the property dp
  grad sync relies on so replicated params cannot drift apart);
- the bf16-fallback floor: tensors below min_quant_numel (or below one
  block per rank) ride the exact psum, bit-identical to all_reduce;
- padding correctness for sizes that do not divide ranks·block;
- non-float inputs fall back to the exact reduction.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.collectives import (all_reduce,
                                             dequantize_blockwise,
                                             quantize_blockwise,
                                             quantized_all_reduce)

N_DEV = 8
BLOCK = 256


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    return make_mesh({"dp": N_DEV})


def _phase_bound(x, block=BLOCK):
    """Analytic elementwise error bound of the two-phase exchange on
    stacked per-rank partials x (n, size), for op="sum": per block,
    0.5·Σ_r s_r (phase-1 rounding of every rank's contribution) plus
    0.5·s₂ where s₂ ≤ (max|exact block sum| + Σ_r 0.5·s_r)/127 (the
    phase-2 scale is computed from the phase-1-rounded sum)."""
    n, size = x.shape
    pad = (-size) % (n * BLOCK)
    xp = np.pad(x, ((0, 0), (0, pad)))
    blocks = xp.reshape(n, -1, block)                  # (n, B, block)
    s1 = np.maximum(np.abs(blocks).max(-1), 0.0) / 127.0
    s1 = np.where(s1 > 0, s1, 0.0)                     # zero blocks: exact
    phase1 = 0.5 * s1.sum(0)                           # (B,)
    exact = blocks.sum(0)                              # (B, block)
    s2 = (np.abs(exact).max(-1) + phase1) / 127.0
    bound = phase1 + 0.5 * s2 + 1e-7                   # (B,)
    return np.repeat(bound, block)[:size]


def test_parity_within_error_model(mesh):
    rng = np.random.RandomState(0)
    # nonuniform block magnitudes so per-block scales actually differ
    x = (rng.randn(N_DEV, 70000)
         * np.exp(rng.uniform(-3, 3, (1, 70000)))).astype(np.float32)
    out = np.asarray(quantized_all_reduce(jnp.asarray(x), mesh, "dp",
                                          op="sum"))
    err = np.abs(out - x.sum(0, dtype=np.float64))
    bound = _phase_bound(x)
    assert (err <= bound).all(), \
        f"error {err.max()} exceeds analytic bound at " \
        f"{np.argmax(err - bound)}"


def test_mean_matches_sum_over_n(mesh):
    rng = np.random.RandomState(1)
    x = rng.randn(N_DEV, 30000).astype(np.float32)
    s = np.asarray(quantized_all_reduce(jnp.asarray(x), mesh, "dp",
                                        op="sum"))
    m = np.asarray(quantized_all_reduce(jnp.asarray(x), mesh, "dp",
                                        op="mean"))
    np.testing.assert_allclose(m, s / N_DEV, rtol=1e-6, atol=1e-7)


def test_bitwise_deterministic(mesh):
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(N_DEV, 50000).astype(np.float32))
    a = np.asarray(quantized_all_reduce(x, mesh, "dp"))
    b = np.asarray(quantized_all_reduce(x, mesh, "dp"))
    assert (a == b).all()


def test_small_tensor_exact_fallback(mesh):
    """Below the floor the exchange IS the exact psum — bit-identical
    to all_reduce (the bf16-fallback contract for biases/LN scales)."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(N_DEV, 300).astype(np.float32))
    q = np.asarray(quantized_all_reduce(x, mesh, "dp", op="sum"))
    exact = np.asarray(all_reduce(x, mesh, "dp", op="sum"))
    assert (q == exact).all()


def test_floor_is_configurable(mesh):
    """Dropping the floor below the tensor size turns quantization ON
    (the result must now differ from the exact sum — proof the floor
    actually routes, not merely tolerated error)."""
    rng = np.random.RandomState(4)
    x = jnp.asarray((100 * rng.randn(N_DEV, N_DEV * BLOCK))
                    .astype(np.float32))
    q = np.asarray(quantized_all_reduce(x, mesh, "dp", op="sum",
                                        min_quant_numel=1))
    exact = np.asarray(x).sum(0)
    assert not np.array_equal(q, exact)
    assert np.abs(q - exact).max() <= _phase_bound(np.asarray(x)).max()


def test_padding_non_divisible_size(mesh):
    rng = np.random.RandomState(5)
    # 12345 is divisible by neither 8 nor 256
    x = rng.randn(N_DEV, 12345).astype(np.float32)
    out = np.asarray(quantized_all_reduce(jnp.asarray(x), mesh, "dp",
                                          op="sum", min_quant_numel=1))
    err = np.abs(out - x.sum(0))
    assert (err <= _phase_bound(x)).all()


def test_nd_shapes_and_shape_preserved(mesh):
    rng = np.random.RandomState(6)
    x = rng.randn(N_DEV, 24, 96, 32).astype(np.float32)
    out = np.asarray(quantized_all_reduce(jnp.asarray(x), mesh, "dp",
                                          op="mean", min_quant_numel=1))
    assert out.shape == (24, 96, 32)
    err = np.abs(out - x.mean(0))
    bound = _phase_bound(x.reshape(N_DEV, -1)).reshape(24, 96, 32)
    assert (err <= bound / N_DEV).all()


def test_int_dtype_falls_back_exact(mesh):
    x = jnp.asarray(np.arange(N_DEV * 100000)
                    .reshape(N_DEV, -1).astype(np.int32))
    out = np.asarray(quantized_all_reduce(x, mesh, "dp", op="sum"))
    assert (out == np.asarray(x).sum(0)).all()


def test_zero_blocks_roundtrip_exact(mesh):
    """All-zero blocks must come back exactly zero (scale-0 blocks get
    scale 1, so 0/1 rounds to int8 0 and dequantizes to 0.0) — a bias
    toward tiny nonzeros here would inject phantom gradient."""
    x = jnp.zeros((N_DEV, N_DEV * BLOCK * 4), jnp.float32)
    out = np.asarray(quantized_all_reduce(x, mesh, "dp",
                                          min_quant_numel=1))
    assert (out == 0.0).all()


def test_quantize_roundtrip_bound():
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(64, BLOCK).astype(np.float32) * 5)
    q, s = quantize_blockwise(x, BLOCK)
    assert q.dtype == jnp.int8 and s.shape == (64,)
    back = np.asarray(dequantize_blockwise(q, s))
    err = np.abs(back - np.asarray(x))
    assert (err <= 0.5 * np.asarray(s)[:, None] + 1e-7).all()
