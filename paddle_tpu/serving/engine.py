"""ServingEngine: shape-bucketed AOT serving over the Predictor.

XLA serves fixed shapes: every novel input signature is a multi-second
compile, and a production frontend that lets request shapes leak into
the executable cache compiles forever (shape churn).  The engine closes
the shape space up front:

- a **bounded bucket ladder** — batch sizes × (optionally) sequence
  lengths, `BucketConfig`.  Every dispatch is padded UP to the smallest
  bucket that fits, so the set of signatures the device ever sees is
  exactly the ladder, precompiled at `start()` (warmup) through
  `Predictor.compile_signature` (AOT, no example data),
- **ragged requests ride the repo's padded-dense convention** — an
  input with a `<name>.seq_len` companion in the saved model's feed
  list is ragged on its leading (time) axis; the engine pads each
  request to the seq bucket and synthesizes the int32 companion with
  true lengths, so kernels mask padding exactly as in training
  (lod_level=1; nested lod_level=2 serving is rejected loudly),
- a request that fits NO bucket (wrong dense shape, over-long
  sequence) fails fast at submit() with a structured
  `BucketMissError` — it never occupies queue capacity and never
  reaches the device.

Steady state is therefore ZERO compiles (asserted by tests and the CI
smoke via `observe.runtime_stats`); a post-warmup compile is emitted as
a loud `serving_compile_post_warmup` event rather than silently eating
seconds of serving capacity.

Threading: `submit()`/`infer()` are safe from any number of frontend
threads; one batcher worker owns dispatch (XLA executions are
internally thread-safe, but one dispatcher keeps the device queue
ordered and the occupancy story simple).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..inference import AnalysisConfig, Predictor
from ..observe.events import RunEventLog
from ..observe.monitoring import runtime_stats
from .admission import (AdmissionController, CircuitBreaker,
                        ExecutorFailureError, ServingError,
                        WeightReloadError)
from .batcher import DynamicBatcher, Request
from .stats import ServingStats


class BucketMissError(ServingError):
    """The request fits no configured shape bucket (structured: carries
    the offending input, its shape, and the allowed buckets)."""

    kind = "bucket_miss"


class BucketMemoryError(ServingError):
    """A configured bucket's PREDICTED peak memory exceeds the device
    budget — raised by start() BEFORE the ladder is AOT-compiled, from
    the observe.memory fit planner's small-batch probes (structured:
    carries the offending buckets with predicted bytes, the budget,
    and the probe evidence)."""

    kind = "bucket_memory"


class BucketConfig:
    """The bounded shape ladder the engine is allowed to compile.

    batch_sizes: ascending batch buckets; the largest is also the
        batcher's max_batch_size.
    seq_lens: ascending sequence-length buckets for ragged inputs
        (None for dense-only models).
    max_buckets: hard cap on |batch_sizes| × |seq_lens| — warmup
        compiles every combination, and an unbounded ladder is exactly
        the shape churn this subsystem exists to prevent.
    """

    def __init__(self, batch_sizes: Sequence[int] = (1, 2, 4, 8),
                 seq_lens: Optional[Sequence[int]] = None,
                 max_buckets: int = 32):
        self.batch_sizes = self._ladder("batch_sizes", batch_sizes)
        self.seq_lens = (self._ladder("seq_lens", seq_lens)
                         if seq_lens is not None else None)
        n = len(self.batch_sizes) * max(1, len(self.seq_lens or ()))
        if n > max_buckets:
            raise ValueError(
                f"{n} shape buckets exceed max_buckets={max_buckets}: "
                f"every bucket is an XLA compile at warmup and a "
                f"resident executable — thin the ladder or raise the "
                f"cap deliberately")
        self.n_buckets = n

    @staticmethod
    def _ladder(name: str, vals) -> Tuple[int, ...]:
        vals = tuple(int(v) for v in vals)
        if not vals or any(v < 1 for v in vals) \
                or list(vals) != sorted(set(vals)):
            raise ValueError(
                f"{name} must be ascending unique positive ints, "
                f"got {vals}")
        return vals

    @staticmethod
    def pick(ladder: Tuple[int, ...], need: int) -> Optional[int]:
        """Smallest bucket >= need (minimum padding waste), or None."""
        for v in ladder:
            if v >= need:
                return v
        return None


class ServingEngine:
    """Dynamic-batching serving endpoint over a saved inference model.

        engine = ServingEngine(model_dir,
                               example_feed={"x": np.zeros(16, "f4")},
                               buckets=BucketConfig((1, 2, 4, 8)))
        engine.start()                      # warmup: compile the ladder
        y = engine.infer({"x": x})          # or submit() -> Future
        engine.close()                      # drain, then stop

    model: a saved-model dir, AnalysisConfig, or an existing Predictor.
    example_feed: one PER-EXAMPLE array per model input (no batch dim;
        ragged inputs use their natural (L, ...) shape) — the dtype and
        trailing-shape template requests are validated against.
    max_wait_ms: batch window — a request waits at most this long for
        co-batching before dispatching underfull.
    queue_capacity: bound on accepted-but-unresolved requests; beyond
        it submit() fast-rejects with QueueFullError (load shedding).
    default_deadline_ms: per-request deadline when the caller sets
        none; expired requests are dropped before dispatch.
    event_log / log_path: observe.RunEventLog (or a path to create
        one) for serving_* telemetry events.
    donate_feeds: donate request buffers to XLA (output reuses input
        memory).  Default: on for TPU backends, off for CPU.  Leave off
        if you run() the shared Predictor yourself with device-resident
        feeds you reuse.
    breaker: serving circuit breaker (admission.CircuitBreaker) —
        `breaker.failure_threshold` CONSECUTIVE dispatch failures flip
        admission to DEGRADED (submits fast-reject with a structured
        CircuitOpenError) until a half-open probe succeeds.  Default: a
        CircuitBreaker(failure_threshold=5, cooldown_s=5).  Pass
        breaker=False to disable.
    warmup_deadline_s: wall-clock budget for the start() bucket-ladder
        warmup (resilience.Deadline): a hung XLA compile raises a
        structured WatchdogTimeout instead of stalling the rollout.
    tracer: an observe.ReqTracer — per-request tracing (observe
        pillar 7): every request carries a RequestTrace with host
        spans at the queue boundaries (queue_wait / batch_form /
        dispatch).  Purely host-side — zero extra device dispatches,
        zero retraces, identical step lowering (pinned by tests).
        None (default) disables tracing; a Fleet passes its own
        traces through `submit(_trace=...)` regardless.
    memory_budget_bytes: device HBM budget the bucket ladder must fit.
        None (default) reads the live device budget
        (observe.memory.device_memory_budget(); None on backends that
        report none, e.g. the CPU test mesh — validation is then
        skipped).  When a budget is known, start() PREDICTS each bucket's
        peak memory from two small probe compiles (batch 1 and 2 at
        each seq bucket) and raises a structured BucketMemoryError for
        impossible buckets BEFORE AOT-compiling the ladder — a
        16-bucket warmup never burns 15 compiles to discover the 16th
        OOMs.  Pass False to disable validation entirely.
    """

    def __init__(self, model: Union[str, AnalysisConfig, Predictor],
                 example_feed: Dict[str, np.ndarray],
                 buckets: Optional[BucketConfig] = None,
                 max_wait_ms: float = 5.0, queue_capacity: int = 128,
                 default_deadline_ms: Optional[float] = None,
                 event_log: Optional[RunEventLog] = None,
                 log_path: Optional[str] = None,
                 stats_window: int = 256,
                 donate_feeds: Optional[bool] = None,
                 breaker: Union[CircuitBreaker, bool, None] = None,
                 warmup_deadline_s: Optional[float] = None,
                 memory_budget_bytes: Union[int, bool, None] = None,
                 tracer=None):
        # duck-typed: anything with run()/compile_signature() serves
        # (a resilience.FlakyPredictor proxy in chaos tests, a custom
        # wrapper in production)
        self.predictor = (model if isinstance(model, Predictor)
                          or (hasattr(model, "run")
                              and hasattr(model, "compile_signature"))
                          else Predictor(model))
        self.buckets = buckets or BucketConfig()
        feed_names = self.predictor.get_input_names()
        nested = [n for n in feed_names if n.endswith(".seq_len2")]
        if nested:
            raise NotImplementedError(
                f"nested (lod_level=2) serving inputs not supported: "
                f"{nested}")
        companions = {n for n in feed_names if n.endswith(".seq_len")}
        self._data_names = [n for n in feed_names
                            if n not in companions]
        self._ragged = {n for n in self._data_names
                        if f"{n}.seq_len" in companions}
        orphan = companions - {f"{n}.seq_len" for n in self._ragged}
        if orphan:
            raise ValueError(f"seq_len companions without a data input: "
                             f"{sorted(orphan)}")
        missing = set(self._data_names) - set(example_feed)
        if missing:
            raise ValueError(
                f"example_feed missing inputs: {sorted(missing)} "
                f"(model feeds: {self._data_names})")
        self._templates = {n: np.asarray(example_feed[n])
                           for n in self._data_names}
        if self._ragged and self.buckets.seq_lens is None:
            raise ValueError(
                f"model has ragged inputs {sorted(self._ragged)} but "
                f"BucketConfig has no seq_lens ladder")
        if not self._ragged and self.buckets.seq_lens is not None:
            raise ValueError(
                "BucketConfig.seq_lens given but the model has no "
                "ragged (.seq_len companion) inputs")
        for n in self._ragged:
            if self._templates[n].ndim < 1:
                raise ValueError(f"ragged input {n!r} example must have "
                                 f"a leading sequence axis")

        if donate_feeds is None:
            import jax

            donate_feeds = jax.default_backend() == "tpu"
        self._donate = bool(donate_feeds)

        self._own_log = None
        if event_log is None and log_path is not None:
            event_log = self._own_log = RunEventLog(
                log_path, meta={"component": "serving_engine"})
        self.stats = ServingStats(event_log=event_log,
                                  window=stats_window)
        self._event_log = event_log
        if breaker is None:
            breaker = CircuitBreaker(failure_threshold=5, cooldown_s=5.0)
        elif breaker is False:
            breaker = None
        self.warmup_deadline_s = warmup_deadline_s
        self.memory_budget_bytes = memory_budget_bytes
        self.tracer = tracer
        self.fit_plan: Optional[Dict[str, Any]] = None
        self.admission = AdmissionController(
            queue_capacity, default_deadline_ms=default_deadline_ms,
            breaker=breaker)
        self.batcher = DynamicBatcher(
            self._dispatch, self.admission,
            max_batch_size=self.buckets.batch_sizes[-1],
            max_wait_ms=max_wait_ms,
            on_deadline_miss=self._on_deadline_miss)
        self._started = False
        self._lock = threading.Lock()
        # fleet surface: replica identity + live weight version
        self.replica_id: Optional[int] = None
        self.model_version = 0
        # observe pillars 7+9 (opt-in, standalone engines; fleets
        # front their own registry/engine instead)
        self._metrics_registry = None
        self._metrics_server = None
        self.alert_engine = None
        self.flight_recorder = None

    def set_replica_id(self, replica_id: int) -> None:
        """Name this engine as fleet replica `replica_id` and stamp the
        id on every event it (and its stats) emits — N replicas sharing
        one RunEventLog stay disambiguated."""
        self.replica_id = int(replica_id)
        if self._event_log is not None \
                and hasattr(self._event_log, "bind"):
            bound = self._event_log.bind(replica_id=self.replica_id)
            self._event_log = bound
            self.stats._event_log = bound

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "ServingEngine":
        """Warmup: AOT-compile every bucket, then open for traffic.
        After this returns, steady-state serving performs zero XLA
        compiles (any later compile is a shape leak and is reported)."""
        with self._lock:
            if self._started:
                raise RuntimeError("engine already started")
            self._started = True
        if self._event_log is not None:
            self._event_log.event(
                "serving_start",
                buckets={"batch_sizes": list(self.buckets.batch_sizes),
                         "seq_lens": list(self.buckets.seq_lens)
                         if self.buckets.seq_lens else None},
                queue_capacity=self.admission.queue_capacity,
                max_wait_ms=self.batcher.max_wait_ms,
                inputs=self._data_names,
                ragged=sorted(self._ragged),
                donate_feeds=self._donate)
        snap = runtime_stats.snapshot()
        t0 = time.perf_counter()
        from ..resilience.watchdog import Deadline

        with Deadline(self.warmup_deadline_s or 0,
                      what="serving warmup (bucket-ladder compile)"):
            # reject impossible buckets BEFORE burning a ladder of
            # compiles on them (BucketMemoryError, structured)
            self._validate_memory_budget()
            for spec in self._bucket_specs():
                self.predictor.compile_signature(
                    spec, donate_feeds=self._donate)
        seconds = time.perf_counter() - t0
        delta = runtime_stats.delta(snap)
        self.stats.record_warmup(self.buckets.n_buckets,
                                 delta["compiles"],
                                 delta["compile_time_s"], seconds)
        self.admission.start()
        self.batcher.start()
        return self

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Graceful shutdown, phase 1: stop admission (new submits get
        ServingClosedError), flush open batch windows, wait for every
        accepted request to resolve.  Idempotent."""
        self.admission.begin_drain()
        ok = self.batcher.drain(timeout_s)
        if self._event_log is not None:
            self.stats.emit("serving_drain", drained=ok)
        return ok

    def close(self, timeout_s: float = 60.0):
        """drain() + stop the worker.  Every future an accepted request
        ever got is resolved by the time this returns — with a result,
        or with a structured ServingError."""
        if self.admission.state == "running":
            self.drain(timeout_s)
        self.batcher.shutdown(timeout_s)
        self.admission.finish_drain()
        if self.alert_engine is not None:
            self.alert_engine.close()
        if self.flight_recorder is not None:
            self.flight_recorder.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        if self._own_log is not None:
            self._own_log.close()

    def __enter__(self) -> "ServingEngine":
        return self.start() if not self._started else self

    def __exit__(self, *exc):
        self.close()
        return False

    def health(self) -> Dict[str, Any]:
        return self.admission.health(
            queue_depth=self.batcher.inflight,
            buckets=self.buckets.n_buckets,
            completed=self.stats.completed,
            executor_failures=self.stats.executor_failures,
            replica_id=self.replica_id,
            model_version=self.model_version,
            post_warmup_compiles=self.stats.post_warmup_compiles())

    # -- unified metrics export + alerts (observe pillars 7+9) ----------
    def metrics_registry(self):
        """Standalone-engine metrics surface: this engine's stats (+
        tracer phases when tracing is on) joined with the process-wide
        runtime/process/memory collectors.  Built once, cached.
        Engines fronted by a Fleet use the fleet's registry instead
        (it merges replicas at scrape time)."""
        if self._metrics_registry is None:
            from ..observe.registry import (MetricsRegistry,
                                            serving_stats_collector,
                                            standard_collectors,
                                            tracer_collector)

            reg = standard_collectors(MetricsRegistry())
            reg.register("serving",
                         serving_stats_collector(self.stats,
                                                 scope="engine"))
            if self.tracer is not None:
                reg.register("reqtrace",
                             tracer_collector(self.tracer))
            self._metrics_registry = reg
        return self._metrics_registry

    def start_metrics_server(self, host: str = "127.0.0.1",
                             port: int = 0):
        """Opt-in /metrics + /healthz (+ /alerts with enable_alerts)
        endpoint for a standalone engine; binds localhost, port=0 =
        ephemeral.  Stopped by close()."""
        if self._metrics_server is not None:
            return self._metrics_server
        from ..observe.registry import MetricsServer

        self._metrics_server = MetricsServer(
            self.metrics_registry(), health_fn=self.health,
            host=host, port=port,
            alerts_fn=(self.alert_engine.state
                       if self.alert_engine is not None
                       else None)).start()
        return self._metrics_server

    def enable_alerts(self, rules=None, interval_s: float = 5.0,
                      flight_dir: Optional[str] = None,
                      recorder_config: Optional[Dict[str, Any]] = None,
                      start: bool = True, **pack_kw):
        """Opt into observe pillar 9 on a standalone engine: the
        `observe.serving_rule_pack` (e2e p99 / error-budget burn /
        post-warmup-compile tripwire; or explicit `rules`) evaluated
        over `metrics_registry()` on a background thread, with an
        optional FlightRecorder bundling diagnostics on every firing
        alert (`flight_dir`).  Pure host — zero device dispatches from
        the engine thread.  Stopped by close()."""
        if self.alert_engine is not None:
            return self.alert_engine
        from ..observe.alerts import AlertEngine, serving_rule_pack
        from ..observe.flightrec import FlightRecorder

        if rules is None:
            rules = serving_rule_pack(**pack_kw)
        elif pack_kw:
            raise ValueError("pack_kw only applies to the default "
                             "rule pack")
        engine = AlertEngine(self.metrics_registry(), rules=rules,
                             interval_s=interval_s,
                             event_log=self._event_log)
        self.metrics_registry().register("alerts", engine.collector())
        if flight_dir is not None:
            self.flight_recorder = FlightRecorder(
                flight_dir, registry=self.metrics_registry(),
                event_log=self._event_log, tracer=self.tracer,
                **(recorder_config or {}))
            self.flight_recorder.attach_engine(engine)
        self.alert_engine = engine
        if self._metrics_server is not None:
            self._metrics_server.alerts_fn = engine.state
        if start:
            engine.start()
        return engine

    # -- fleet surface: hot weight reload -------------------------------
    def reload(self, source, version: Optional[int] = None
               ) -> Dict[str, Any]:
        """Hot weight reload: swap the live predictor's device-resident
        parameters for same-shape arrays — the same-shape contract is
        asserted (that is what guarantees the per-bucket executables
        are reused with ZERO recompiles) and the swap is a single
        attribute rebind, so each dispatch runs wholly on the old or
        wholly on the new weights (the batcher worker reads the param
        dict once per executable call — drain-to-batch-boundary for
        free).  `source` is a sharded-checkpoint dir (io.load_sharded)
        or a name→array mapping.  Structured WeightReloadError on
        mismatch; the old weights keep serving."""
        t0 = time.perf_counter()
        params = self._materialize_params(source)
        live = self.predictor._params
        missing = sorted(set(live) - set(params))
        if missing:
            raise WeightReloadError(
                f"reload source missing {len(missing)} parameter(s): "
                f"{missing[:4]}{' ...' if len(missing) > 4 else ''}",
                replica_id=self.replica_id, missing=missing)
        mismatched = [
            {"name": n,
             "live": [list(live[n].shape), str(live[n].dtype)],
             "new": [list(params[n].shape), str(params[n].dtype)]}
            for n in live
            if (tuple(params[n].shape) != tuple(live[n].shape)
                or params[n].dtype != live[n].dtype)]
        if mismatched:
            raise WeightReloadError(
                f"{len(mismatched)} parameter(s) change shape/dtype — "
                f"a same-shape swap is the zero-recompile contract; "
                f"first: {mismatched[0]}",
                replica_id=self.replica_id, mismatched=mismatched)
        new_version = (self.model_version + 1 if version is None
                       else int(version))
        self.predictor._params = {n: params[n] for n in live}
        self.model_version = new_version
        pause_ms = (time.perf_counter() - t0) * 1e3
        self.stats.record_reload(pause_ms)
        if self._event_log is not None:
            self._event_log.event(
                "serving_reload", version=new_version,
                pause_ms=round(pause_ms, 3),
                source=source if isinstance(source, str) else "arrays")
        return {"version": new_version, "pause_ms": round(pause_ms, 3)}

    def _materialize_params(self, source) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        from ..core.executor import RNG_STATE_VAR

        if isinstance(source, str):
            from .. import io as fluid_io
            from ..core.executor import Executor, scope_guard

            pred = self.predictor
            with scope_guard(pred._scope):
                fluid_io.load_sharded(
                    Executor(), source, main_program=pred._program,
                    vars=[pred._program.global_block().var(n)
                          for n in pred._params
                          if n in pred._program.global_block().vars])
            src = {n: v for n, v in pred._scope.vars.items()
                   if v is not None and n != RNG_STATE_VAR}
        else:
            src = dict(source)
        return {n: jax.device_put(jnp.asarray(v))
                for n, v in src.items()
                if n in self.predictor._params}

    def _breaker_event(self, kind: str, **fields):
        """serving_breaker_open/close: state-transition events a pager
        rule can key on."""
        if self._event_log is not None:
            self._event_log.event(
                kind, state=self.admission.state,
                breaker=self.admission.breaker.snapshot(), **fields)

    # -- request path ---------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None,
               _trace=None) -> Future:
        """Accept one request (PER-EXAMPLE feeds, no batch dim) and
        return a Future of its fetch list.  Raises BucketMissError /
        QueueFullError / ServingClosedError synchronously — a rejected
        request never occupies queue capacity.  `_trace`: a fleet
        router's RequestTrace to continue (the engine then only adds
        spans; the router owns the trace lifecycle)."""
        trace = _trace
        if trace is None and self.tracer is not None:
            trace = self.tracer.new_trace("serving")
        feeds, max_len = self._normalize(feed)
        deadline = self.admission.deadline_for(deadline_ms)
        req = Request(feeds, deadline=deadline, max_len=max_len,
                      trace=trace)
        try:
            self.batcher.submit(req)
        except ServingError as e:
            if e.kind == "queue_full":
                self.stats.record_shed()
            elif e.kind == "circuit_open":
                self.stats.record_circuit_reject()
            if trace is not None and not trace.fleet_owned \
                    and self.tracer is not None:
                trace.point("rejected", reject=e.kind,
                            replica_id=self.replica_id)
                self.tracer.finish(trace, error=e)
            raise
        self.stats.record_submit(self.batcher.queue_depth)
        return req.future

    def infer(self, feed: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None,
              timeout_s: Optional[float] = None) -> List[np.ndarray]:
        """Synchronous submit()+result() convenience."""
        return self.submit(feed, deadline_ms=deadline_ms).result(
            timeout_s)

    # -- internals ------------------------------------------------------
    def _on_deadline_miss(self, req: Request):
        self.stats.record_deadline_miss()
        tr = req.trace
        if tr is not None and not tr.fleet_owned \
                and self.tracer is not None:
            tr.add("queue_wait", req.t_submit, time.monotonic(),
                   replica_id=self.replica_id, expired=True)
            self.tracer.finish(tr, error=RuntimeError(
                "deadline expired while queued"))

    def _normalize(self, feed: Dict[str, np.ndarray]
                   ) -> Tuple[Dict[str, np.ndarray], Optional[int]]:
        unknown = set(feed) - set(self._data_names)
        if unknown:
            raise ValueError(
                f"unknown inputs {sorted(unknown)}; model feeds are "
                f"{self._data_names} (seq_len companions are "
                f"synthesized by the engine)")
        missing = set(self._data_names) - set(feed)
        if missing:
            raise ValueError(f"missing inputs: {sorted(missing)}")
        out: Dict[str, np.ndarray] = {}
        max_len: Optional[int] = None
        for n in self._data_names:
            tpl = self._templates[n]
            v = np.asarray(feed[n])
            if v.dtype != tpl.dtype:
                v = v.astype(tpl.dtype)  # serving frontends send f64
            if n in self._ragged:
                if v.ndim != tpl.ndim or v.shape[1:] != tpl.shape[1:]:
                    raise BucketMissError(
                        f"ragged input {n!r}: got shape {v.shape}, "
                        f"want (L,) + {tpl.shape[1:]}",
                        input=n, got_shape=list(v.shape),
                        want_tail=list(tpl.shape[1:]))
                length = v.shape[0]
                if length < 1:
                    raise BucketMissError(
                        f"ragged input {n!r} is empty", input=n,
                        got_shape=list(v.shape))
                if BucketConfig.pick(self.buckets.seq_lens,
                                     length) is None:
                    self.stats.record_bucket_miss()
                    raise BucketMissError(
                        f"ragged input {n!r} length {length} exceeds "
                        f"the largest seq bucket "
                        f"{self.buckets.seq_lens[-1]}",
                        input=n, length=length,
                        seq_lens=list(self.buckets.seq_lens))
                max_len = length if max_len is None \
                    else max(max_len, length)
            elif v.shape != tpl.shape:
                self.stats.record_bucket_miss()
                raise BucketMissError(
                    f"input {n!r}: got shape {v.shape}, bucketed "
                    f"shapes require the per-example template "
                    f"{tpl.shape}", input=n, got_shape=list(v.shape),
                    want_shape=list(tpl.shape))
            out[n] = v
        return out, max_len

    def _spec_for(self, bs: int, sl: Optional[int]):
        """ShapeDtypeStruct feed spec of one (batch, seq) bucket."""
        import jax

        spec: Dict[str, jax.ShapeDtypeStruct] = {}
        for n, tpl in self._templates.items():
            if n in self._ragged:
                shape = (bs, sl) + tpl.shape[1:]
                spec[f"{n}.seq_len"] = jax.ShapeDtypeStruct(
                    (bs,), np.int32)
            else:
                shape = (bs,) + tpl.shape
            spec[n] = jax.ShapeDtypeStruct(shape, tpl.dtype)
        return spec

    def _bucket_specs(self):
        """ShapeDtypeStruct feed specs for every ladder combination."""
        for bs in self.buckets.batch_sizes:
            for sl in (self.buckets.seq_lens or (None,)):
                yield self._spec_for(bs, sl)

    def _validate_memory_budget(self):
        """Predict every bucket's peak memory BEFORE the ladder warmup
        and raise a structured BucketMemoryError for impossible buckets.

        Inference peak is affine in batch at a fixed seq bucket (params
        are constant, per-example activations scale), so two small
        probe compiles per seq bucket (the observe.memory plan_fit
        technique) predict the whole batch ladder — a 16-bucket warmup
        never burns 15 compiles to discover the 16th OOMs.  Probe
        executables land in the predictor's signature cache, so ladder
        buckets at the probe sizes are not compiled twice.  Records the
        full prediction table in `self.fit_plan`; skips silently (plan
        tagged) when no budget is known or the backend exposes no
        memory analysis."""
        budget = self.memory_budget_bytes
        if budget is False:
            return
        if budget is None or budget is True:
            from ..observe.memory import device_memory_budget

            budget = device_memory_budget()
        if not budget:
            self.fit_plan = {"skipped": "no device budget known",
                             "budget_bytes": None}
            return
        from ..observe.memory import (PLAN_FIT_REL_TOL,
                                      compiled_peak_bytes)

        probe_bs = tuple(b for b in (1, 2)
                         if b <= self.buckets.batch_sizes[-1]) or (1,)
        buckets_plan: List[Dict[str, Any]] = []
        bad: List[Dict[str, Any]] = []
        for sl in (self.buckets.seq_lens or (None,)):
            peaks = []
            for b in probe_bs:
                compiled = self.predictor.compile_signature(
                    self._spec_for(b, sl), donate_feeds=self._donate)
                peak = compiled_peak_bytes(compiled)
                if peak is None:
                    self.fit_plan = {
                        "skipped": "backend exposes no memory analysis",
                        "budget_bytes": int(budget)}
                    return
                peaks.append(int(peak))
            if len(peaks) == 2:
                slope = (peaks[1] - peaks[0]) / float(
                    probe_bs[1] - probe_bs[0])
                intercept = peaks[0] - slope * probe_bs[0]
            else:
                slope, intercept = 0.0, float(peaks[0])
            for bs in self.buckets.batch_sizes:
                if bs in probe_bs:
                    pred, exact = peaks[probe_bs.index(bs)], True
                else:
                    pred = int(round(intercept + slope * bs))
                    exact = False
                row = {"batch_size": bs, "seq_len": sl,
                       "predicted_peak_bytes": pred, "exact": exact,
                       "fits": pred <= budget}
                buckets_plan.append(row)
                if not row["fits"]:
                    bad.append(row)
        self.fit_plan = {
            "budget_bytes": int(budget),
            "probe_batches": list(probe_bs),
            "rel_tol": PLAN_FIT_REL_TOL,
            "buckets": buckets_plan,
        }
        if self._event_log is not None:
            self._event_log.event("serving_memory_plan", **self.fit_plan)
        if bad:
            raise BucketMemoryError(
                f"{len(bad)}/{len(buckets_plan)} configured buckets "
                f"predicted to exceed the device memory budget "
                f"({budget / 1e9:.2f} GB): "
                + ", ".join(f"bs{r['batch_size']}"
                            + (f"/seq{r['seq_len']}"
                               if r['seq_len'] else "")
                            + f"≈{r['predicted_peak_bytes'] / 1e9:.2f}GB"
                            for r in bad[:4])
                + (" ..." if len(bad) > 4 else ""),
                budget_bytes=int(budget),
                offending_buckets=bad,
                probe_batches=list(probe_bs),
                plan=buckets_plan)

    def _dispatch(self, requests: Sequence[Request]):
        """Batcher callback: pad to the smallest fitting bucket,
        dispatch ONE executable call, demux outputs to futures."""
        t_form = time.monotonic()  # queue_wait ends / batch_form begins
        n = len(requests)
        bucket_b = BucketConfig.pick(self.buckets.batch_sizes, n)
        assert bucket_b is not None, (n, self.buckets.batch_sizes)
        bucket_s = None
        if self._ragged:
            need = max(r.max_len for r in requests)
            bucket_s = BucketConfig.pick(self.buckets.seq_lens, need)
            assert bucket_s is not None, (need, self.buckets.seq_lens)

        feed: Dict[str, np.ndarray] = {}
        elems_real = elems_padded = 0.0
        for name, tpl in self._templates.items():
            if name in self._ragged:
                arr = np.zeros((bucket_b, bucket_s) + tpl.shape[1:],
                               dtype=tpl.dtype)
                # pad rows get length 1, not 0: a zero-length row can
                # divide-by-zero inside masked kernels (avg pools), and
                # its output is discarded at demux anyway
                lens = np.ones((bucket_b,), np.int32)
                for i, r in enumerate(requests):
                    v = r.feeds[name]
                    arr[i, :v.shape[0]] = v
                    lens[i] = v.shape[0]
                feed[name] = arr
                feed[f"{name}.seq_len"] = lens
                row = float(np.prod(tpl.shape[1:], dtype=np.float64)
                            or 1.0)
                elems_real += sum(
                    r.feeds[name].shape[0] for r in requests) * row
                elems_padded += bucket_b * bucket_s * row
            else:
                arr = np.zeros((bucket_b,) + tpl.shape, dtype=tpl.dtype)
                for i, r in enumerate(requests):
                    arr[i] = r.feeds[name]
                feed[name] = arr
                row = float(tpl.size or 1.0)
                elems_real += n * row
                elems_padded += bucket_b * row
        version = self.model_version  # the weights this batch runs on
        t_disp = time.monotonic()     # batch_form ends / dispatch begins
        for r in requests:
            if r.trace is not None:
                r.trace.add("queue_wait", r.t_submit, t_form,
                            replica_id=self.replica_id)
                r.trace.add("batch_form", t_form, t_disp,
                            replica_id=self.replica_id, batch=n,
                            bucket=bucket_b)
        t0 = time.perf_counter()
        try:
            if self.replica_id is not None:
                # fleet chaos points (resilience.chaos): an armed kill
                # raises here and rides the REAL dispatch-failure path
                # below — the batch fails with the structured retryable
                # wrapper a router fails over
                from ..resilience import chaos

                chaos.delaypoint(f"replica:{self.replica_id}:delay")
                chaos.failpoint(f"replica:{self.replica_id}:kill")
            outs = self.predictor.run(feed)
        except BaseException as e:
            # one executor outcome per dispatch feeds the breaker; the
            # batcher resolves every future in the batch with the
            # structured wrapper raised here (never silently dropped)
            self.stats.record_executor_failure()
            if self.admission.record_dispatch_result(False) == "opened":
                self._breaker_event("serving_breaker_open",
                                    failed_batch_size=n)
            err = ExecutorFailureError(
                f"executor dispatch failed for batch of {n}: "
                f"{type(e).__name__}: {e}",
                error_type=type(e).__name__, batch_size=n)
            t_err = time.monotonic()
            for r in requests:
                if r.trace is not None:
                    r.trace.add("dispatch", t_disp, t_err,
                                replica_id=self.replica_id, batch=n,
                                error=type(e).__name__)
                    if not r.trace.fleet_owned \
                            and self.tracer is not None:
                        self.tracer.finish(r.trace, error=err)
            raise err from e
        exec_ms = (time.perf_counter() - t0) * 1e3
        t_done = time.monotonic()
        for r in requests:
            if r.trace is not None:
                r.trace.add("dispatch", t_disp, t_done,
                            replica_id=self.replica_id, batch=n)
        if self.admission.record_dispatch_result(True) == "closed":
            self._breaker_event("serving_breaker_close")
        self.stats.record_batch(n, bucket_b, elems_real, elems_padded,
                                exec_ms)
        now = time.monotonic()
        for i, r in enumerate(requests):
            # fetches are batch-major; anything without a leading batch
            # axis (a scalar metric) is handed back whole
            res = [o[i] if (getattr(o, "ndim", 0) >= 1
                            and o.shape[0] == bucket_b) else o
                   for o in outs]
            r.future.model_version = version
            r.future.set_result(res)
            self.stats.record_done((now - r.t_submit) * 1e3)
            if r.trace is not None and not r.trace.fleet_owned \
                    and self.tracer is not None:
                self.tracer.finish(r.trace)
        self.stats.maybe_emit()
