"""SRL sequence-tagging book model (models/sequence_tagging.py —
reference book test_label_semantic_roles.py): db_lstm emission stack +
linear-chain CRF trains to a decodable state on synthetic tagged data.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import sequence_tagging


def test_srl_db_lstm_crf_converges():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 1
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = sequence_tagging.build_model(
            word_dict_len=50, label_dict_len=5, pred_dict_len=10,
            max_length=8, word_dim=16, hidden_dim=16, depth=2,
            learning_rate=0.05)
        exe = fluid.Executor()
        exe.run(startup)
        batch = sequence_tagging.make_fake_batch(
            16, max_length=8, word_dict_len=50, label_dict_len=5,
            pred_dict_len=10)
        losses = []
        for _ in range(30):
            lv, = exe.run(main, feed=batch, fetch_list=[model["loss"]])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

        # decode path: viterbi tags within the label space, padded tail
        # untouched by the decode's masking
        dv, = exe.run(main, feed=batch,
                      fetch_list=[model["crf_decode"]])
        dv = np.asarray(dv)
        lens = batch["word.seq_len"]
        assert dv.shape[0] == 16
        for i, L in enumerate(lens):
            assert (dv[i, :L] >= 0).all() and (dv[i, :L] < 5).all()

        # training improved tag accuracy over the valid positions vs
        # a frozen-init baseline would be flaky to assert exactly;
        # instead require the decode to agree with targets on a
        # majority of positions after training
        tgt = batch["target"]
        correct = total = 0
        for i, L in enumerate(lens):
            correct += int((dv[i, :L] == tgt[i, :L]).sum())
            total += int(L)
        assert correct / total > 0.6, correct / total


def test_parameter_sharing_by_name():
    """fluid semantics: an explicitly named ParamAttr REUSES the
    existing parameter; guards fire on shape mismatch, non-parameter
    collisions, and re-configured attrs."""
    import pytest

    from paddle_tpu import layers
    from paddle_tpu.param_attr import ParamAttr

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        a = layers.data("a", shape=[4], dtype="int64")
        b = layers.data("b", shape=[4], dtype="int64")
        e1 = layers.embedding(a, size=[10, 8],
                              param_attr=ParamAttr(name="shared_emb"))
        e2 = layers.embedding(b, size=[10, 8],
                              param_attr=ParamAttr(name="shared_emb"))
        # exactly ONE parameter exists
        params = [v for v in main.list_vars()
                  if getattr(v, "trainable", False)
                  and "shared_emb" in v.name]
        assert len(params) == 1

        with pytest.raises(ValueError, match="mismatched shape"):
            layers.embedding(a, size=[11, 8],
                             param_attr=ParamAttr(name="shared_emb"))
        with pytest.raises(ValueError, match="learning_rate"):
            layers.embedding(a, size=[10, 8],
                             param_attr=ParamAttr(name="shared_emb",
                                                  learning_rate=0.5))
        with pytest.raises(ValueError, match="non-parameter"):
            layers.embedding(a, size=[10, 8],
                             param_attr=ParamAttr(name="a"))

        # training through both paths updates the single table
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            layers.mean(layers.elementwise_add(
                layers.reduce_sum(e1), layers.reduce_sum(e2))))
        exe = fluid.Executor()
        exe.run(startup)
        before = np.asarray(
            fluid.global_scope().find_var(params[0].name)).copy()
        feed = {"a": np.arange(8).reshape(2, 4).astype(np.int64),
                "b": np.arange(8).reshape(2, 4).astype(np.int64)}
        exe.run(main, feed=feed, fetch_list=[])
        after = np.asarray(
            fluid.global_scope().find_var(params[0].name))
        assert not np.allclose(before, after)
