"""Optimizer update ops.

Covers the reference optimizer op corpus (SURVEY.md §2.2 "Optimizers";
reference: paddle/fluid/operators/optimizers/*_op.cc — sgd, momentum,
lars_momentum, adam, adamax, adagrad, decayed_adagrad, adadelta, rmsprop,
ftrl, proximal_gd, proximal_adagrad).  Each op consumes Param/Grad plus
accumulator state and emits the updated values; the Executor writes them
back to the persistable scope vars, so the whole update fuses into the
jitted train step.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import SparseGrad
from .common import first, opt_in, out


def _lr(ins):
    return first(ins, "LearningRate").reshape(())


@register_op("sgd")
def sgd(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    if isinstance(g, SparseGrad):
        # SelectedRows path (reference: optimizers/sgd_op.h SelectedRows
        # kernel): scatter-add only the touched rows; duplicate ids sum
        # naturally.
        return {"ParamOut": [p.at[g.ids].add(-_lr(ins) * g.rows)]}
    return {"ParamOut": [p - _lr(ins) * g]}


@register_op("momentum")
def momentum(ctx, ins, attrs):
    p, g, v = first(ins, "Param"), first(ins, "Grad"), first(ins, "Velocity")
    mu = attrs["mu"]
    lr = _lr(ins)
    if isinstance(g, SparseGrad):
        # lazy rows-only update with merged duplicates (reference:
        # optimizers/momentum_op.h SparseMomentumFunctor)
        valid, ids, rows = g.merged()
        v_rows = mu * v[ids] + rows
        if attrs.get("use_nesterov", False):
            p_delta = -(rows + mu * v_rows) * lr
        else:
            p_delta = -lr * v_rows
        keep = valid[:, None]
        v_new = v.at[ids].add(jnp.where(keep, v_rows - v[ids], 0.0))
        p_new = p.at[ids].add(jnp.where(keep, p_delta, 0.0))
        return {"ParamOut": [p_new], "VelocityOut": [v_new]}
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op("lars_momentum")
def lars_momentum(ctx, ins, attrs):
    p, g, v = first(ins, "Param"), first(ins, "Grad"), first(ins, "Velocity")
    mu = attrs["mu"]
    lars_coeff = attrs.get("lars_coeff", 0.001)
    lars_wd = attrs.get("lars_weight_decay", 0.0005)
    lr = _lr(ins)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * lars_coeff * p_norm / (
        g_norm + lars_wd * p_norm + 1e-12)
    v_new = mu * v + local_lr * (g + lars_wd * p)
    return {"ParamOut": [p - v_new], "VelocityOut": [v_new]}


@register_op("adam")
def adam(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    m1, m2 = first(ins, "Moment1"), first(ins, "Moment2")
    b1p = first(ins, "Beta1Pow").reshape(())
    b2p = first(ins, "Beta2Pow").reshape(())
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins) * jnp.sqrt(1 - b2p) / (1 - b1p)
    beta_pows = {"Beta1PowOut": [(b1p * beta1).reshape((1,))],
                 "Beta2PowOut": [(b2p * beta2).reshape((1,))]}
    if isinstance(g, SparseGrad):
        # lazy sparse Adam with merged duplicate rows (reference:
        # optimizers/adam_op.h SparseAdamFunctor over merged SelectedRows
        # grad): moments and param update touch only the gradient's rows.
        valid, ids, rows = g.merged()
        m1r = beta1 * m1[ids] + (1 - beta1) * rows
        m2r = beta2 * m2[ids] + (1 - beta2) * jnp.square(rows)
        p_delta = -lr * m1r / (jnp.sqrt(m2r) + eps)
        keep = valid[:, None]
        m1n = m1.at[ids].add(jnp.where(keep, m1r - m1[ids], 0.0))
        m2n = m2.at[ids].add(jnp.where(keep, m2r - m2[ids], 0.0))
        p_new = p.at[ids].add(jnp.where(keep, p_delta, 0.0))
        return {"ParamOut": [p_new], "Moment1Out": [m1n],
                "Moment2Out": [m2n], **beta_pows}
    m1n = beta1 * m1 + (1 - beta1) * g
    m2n = beta2 * m2 + (1 - beta2) * jnp.square(g)
    p_new = p - lr * m1n / (jnp.sqrt(m2n) + eps)
    return {
        "ParamOut": [p_new], "Moment1Out": [m1n], "Moment2Out": [m2n],
        **beta_pows,
    }


@register_op("adamax")
def adamax(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    m, inf = first(ins, "Moment"), first(ins, "InfNorm")
    b1p = first(ins, "Beta1Pow").reshape(())
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins) / (1 - b1p)
    m_new = beta1 * m + (1 - beta1) * g
    inf_new = jnp.maximum(beta2 * inf, jnp.abs(g))
    p_new = p - lr * m_new / (inf_new + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new],
            "InfNormOut": [inf_new]}


@register_op("adagrad")
def adagrad(ctx, ins, attrs):
    p, g, m = first(ins, "Param"), first(ins, "Grad"), first(ins, "Moment")
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, SparseGrad):
        # reference: optimizers/adagrad_op.h SparseAdagradFunctor (merged
        # duplicate rows, lazy row updates)
        valid, ids, rows = g.merged()
        m_rows = m[ids] + jnp.square(rows)
        p_delta = -_lr(ins) * rows / (jnp.sqrt(m_rows) + eps)
        keep = valid[:, None]
        m_new = m.at[ids].add(jnp.where(keep, jnp.square(rows), 0.0))
        p_new = p.at[ids].add(jnp.where(keep, p_delta, 0.0))
        return {"ParamOut": [p_new], "MomentOut": [m_new]}
    m_new = m + jnp.square(g)
    p_new = p - _lr(ins) * g / (jnp.sqrt(m_new) + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new]}


@register_op("decayed_adagrad")
def decayed_adagrad(ctx, ins, attrs):
    p, g, m = first(ins, "Param"), first(ins, "Grad"), first(ins, "Moment")
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    p_new = p - _lr(ins) * g / (jnp.sqrt(m_new) + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new]}


@register_op("adadelta")
def adadelta(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    avg_sq_g = first(ins, "AvgSquaredGrad")
    avg_sq_u = first(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {"ParamOut": [p + update], "AvgSquaredGradOut": [g2],
            "AvgSquaredUpdateOut": [u2]}


@register_op("rmsprop")
def rmsprop(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    ms = first(ins, "MeanSquare")
    mom = first(ins, "Moment")
    eps = attrs.get("epsilon", 1e-10)
    decay = attrs.get("decay", 0.9)
    mu = attrs.get("momentum", 0.0)
    lr = _lr(ins)
    if attrs.get("centered", False):
        mg = first(ins, "MeanGrad")
        ms_new = decay * ms + (1 - decay) * jnp.square(g)
        mg_new = decay * mg + (1 - decay) * g
        mom_new = mu * mom + lr * g / jnp.sqrt(
            ms_new - jnp.square(mg_new) + eps)
        return {"ParamOut": [p - mom_new], "MeanSquareOut": [ms_new],
                "MomentOut": [mom_new], "MeanGradOut": [mg_new]}
    ms_new = decay * ms + (1 - decay) * jnp.square(g)
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": [p - mom_new], "MeanSquareOut": [ms_new],
            "MomentOut": [mom_new]}


@register_op("ftrl")
def ftrl(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    sq, lin = first(ins, "SquaredAccumulator"), first(ins, "LinearAccumulator")
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    x = l1 * jnp.sign(new_lin) - new_lin
    p_new = jnp.where(jnp.abs(new_lin) > l1, x / denom, 0.0)
    return {"ParamOut": [p_new], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register_op("proximal_gd")
def proximal_gd(ctx, ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": [p_new]}


@register_op("proximal_adagrad")
def proximal_adagrad(ctx, ins, attrs):
    p, g, m = first(ins, "Param"), first(ins, "Grad"), first(ins, "Moment")
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    m_new = m + jnp.square(g)
    eff_lr = lr / jnp.sqrt(m_new)
    prox = p - eff_lr * g
    p_new = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - eff_lr * l1, 0.0) / (1.0 + eff_lr * l2)
    return {"ParamOut": [p_new], "MomentOut": [m_new]}


@register_op("average_accumulates")
def average_accumulates(ctx, ins, attrs):
    """Parameter-averaging window accumulators (reference:
    optimizers/average_accumulates_op.cc, driving ModelAverage):

      num_updates += 1;  num_accumulates += 1;  sum_1 += param
      if num_updates % max_acc == 0:  sum_2 += sum_1; sum_1 = 0
      if num_accumulates >= min_window and
         num_accumulates >= min(max_window, num_updates * window_rate):
          sum_3 = sum_1 + sum_2; sum_1 = sum_2 = 0
          old_num_accumulates = num_accumulates; num_accumulates = 0
    """
    p = first(ins, "Param")
    s1 = first(ins, "Sum1")
    s2 = first(ins, "Sum2")
    s3 = first(ins, "Sum3")
    num_acc = first(ins, "NumAccumulates").reshape(())
    old_num = first(ins, "OldNumAccumulates").reshape(())
    num_upd = first(ins, "NumUpdates").reshape(())
    rate = float(attrs.get("average_window", 0.0))
    max_acc = int(attrs.get("max_average_window", 10000))
    min_w = int(attrs.get("min_average_window", 10000))

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p
    roll = (num_upd % max(max_acc, 1)) == 0
    s2 = jnp.where(roll, s2 + s1, s2)
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)
    window = jnp.minimum(jnp.asarray(float(max_acc)),
                         num_upd.astype(jnp.float32) * rate)
    emit = (num_acc >= min_w) & (num_acc.astype(jnp.float32) >= window)
    s3 = jnp.where(emit, s1 + s2, s3)
    s1 = jnp.where(emit, jnp.zeros_like(s1), s1)
    s2 = jnp.where(emit, jnp.zeros_like(s2), s2)
    old_num = jnp.where(emit, num_acc, old_num)
    num_acc = jnp.where(emit, jnp.zeros_like(num_acc), num_acc)
    return {"Sum1Out": [s1], "Sum2Out": [s2], "Sum3Out": [s3],
            "NumAccumulatesOut": [num_acc.reshape((1,))],
            "OldNumAccumulatesOut": [old_num.reshape((1,))],
            "NumUpdatesOut": [num_upd.reshape((1,))]}


@register_op("ema_accumulate")
def ema_accumulate(ctx, ins, attrs):
    """Exponential moving average of a param (reference: fluid's
    ExponentialMovingAverage builds this from scale/sum ops;
    one fused op here): ema = decay * ema + (1 - decay) * param."""
    p = first(ins, "Param")
    ema = first(ins, "Ema")
    decay = float(attrs.get("decay", 0.999))
    return {"EmaOut": [decay * ema + (1.0 - decay) * p]}
