"""Executor end-to-end: startup init, train steps, state updates.

Mirrors the reference's executor tests + book tests
(python/paddle/fluid/tests/book/test_fit_a_line.py,
test_recognize_digits.py).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _scope():
    return fluid.Scope()


def test_startup_initializes_params():
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3)
        exe = fluid.Executor()
        exe.run(startup)
        w = [p for p in main.all_parameters() if p.shape == (4, 3)][0]
        val = scope.find_var(w.name)
        assert val is not None and val.shape == (4, 3)
        # Xavier init: non-zero, bounded
        arr = np.asarray(val)
        assert np.abs(arr).max() <= np.sqrt(6.0 / 7) + 1e-6
        assert np.abs(arr).max() > 0


def test_forward_fetch():
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[2], dtype="float32")
        y = layers.scale(x, scale=3.0, bias=1.0)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
        (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
        np.testing.assert_allclose(out, xv * 3 + 1, rtol=1e-6)


def test_fit_a_line_converges():
    """Linear regression must fit y = 2x + 3 (book test_fit_a_line)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    rng = np.random.RandomState(0)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[1], dtype="float32")
        label = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        opt = fluid.optimizer.SGDOptimizer(learning_rate=0.05)
        opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for _ in range(200):
            xv = rng.rand(16, 1).astype(np.float32)
            yv = 2 * xv + 3
            (lv,) = exe.run(main, feed={"x": xv, "y": yv},
                            fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < 0.05, f"final loss {losses[-1]}"
        assert losses[-1] < losses[0] * 0.1


def test_mnist_mlp_learns():
    """Softmax classifier on separable synthetic data (book
    test_recognize_digits MLP, shrunk)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    rng = np.random.RandomState(1)
    n_cls = 4
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        img = layers.data(name="img", shape=[16], dtype="float32")
        lbl = layers.data(name="label", shape=[1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=n_cls)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, lbl))
        acc = layers.accuracy(layers.softmax(logits), lbl, k=1)
        fluid.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        centers = rng.randn(n_cls, 16).astype(np.float32) * 3
        accs = []
        for _ in range(120):
            y = rng.randint(0, n_cls, size=(64, 1))
            xv = centers[y[:, 0]] + rng.randn(64, 16).astype(np.float32)
            lv, av = exe.run(main,
                             feed={"img": xv, "label": y.astype(np.int64)},
                             fetch_list=[loss, acc])
            accs.append(float(av))
        assert np.mean(accs[-10:]) > 0.9


def test_adam_accumulators_update():
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[2], dtype="float32")
        y = layers.fc(x, size=1, bias_attr=False)
        loss = layers.mean(y)
        fluid.optimizer.AdamOptimizer(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.ones((4, 2), dtype=np.float32)
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        w = main.all_parameters()[0]
        b1p = scope.find_var(f"{w.name}.beta1_pow_acc")
        assert b1p is not None
        np.testing.assert_allclose(np.asarray(b1p), [0.9 ** 2], rtol=1e-5)


def test_batch_norm_moving_stats_update():
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[3, 8, 8], dtype="float32")
        y = layers.batch_norm(x, momentum=0.5,
                              moving_mean_name="bn_mean",
                              moving_variance_name="bn_var")
        loss = layers.mean(y)
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).randn(8, 3, 8, 8).astype(np.float32)
        xv = xv * 2.0 + 5.0
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        mean_after = np.asarray(scope.find_var("bn_mean"))
        # moving mean moved halfway (momentum=0.5) toward ~5
        assert np.all(mean_after > 1.5), mean_after


def test_rng_varies_across_steps():
    main, startup = fluid.Program(), fluid.Program()
    scope = _scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[64], dtype="float32")
        d = layers.dropout(x, dropout_prob=0.5)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.ones((2, 64), dtype=np.float32)
        (a,) = exe.run(main, feed={"x": xv}, fetch_list=[d])
        (b,) = exe.run(main, feed={"x": xv}, fetch_list=[d])
        assert not np.allclose(a, b), "dropout mask must differ per step"
