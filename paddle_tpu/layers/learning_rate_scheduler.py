"""Learning-rate schedules as in-graph ops over a step counter.

reference: python/paddle/fluid/layers/learning_rate_scheduler.py —
noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup.
Like fluid, the schedule is part of the program: a persistable int64
global-step var is incremented every step and the lr var is recomputed
from it inside the same XLA computation.
"""

from __future__ import annotations

import math

from ..core.registry import register_op
from ..initializer import Constant
from ..layer_helper import LayerHelper

_COUNTER_NAME = "@lr_decay_counter@"


def _decay_step_counter(begin=0):
    """Persistable global step, incremented once per executed program run
    (reference learning_rate_scheduler.py _decay_step_counter /
    autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    counter = helper.create_or_get_global_variable(
        _COUNTER_NAME, shape=[1], dtype="float32",
        initializer=Constant(float(begin)))
    block = helper.main_program.global_block()
    if not any(op.type == "increment" and
               op.output("Out") == [counter.name]
               for op in block.ops):
        block.append_op(type="increment", inputs={"X": [counter]},
                        outputs={"Out": [counter]}, attrs={"step": 1.0})
    return counter


import jax.numpy as jnp


@register_op("lr_schedule")
def _lr_schedule(ctx, ins, attrs):
    step = ins["Step"][0].reshape(()).astype(jnp.float32)
    kind = attrs["kind"]
    p = attrs
    if kind == "noam":
        d = p["d_model"]
        warmup = p["warmup_steps"]
        lr = d ** -0.5 * jnp.minimum(step ** -0.5, step * warmup ** -1.5)
    elif kind == "exponential":
        e = step / p["decay_steps"]
        if p["staircase"]:
            e = jnp.floor(e)
        lr = p["learning_rate"] * p["decay_rate"] ** e
    elif kind == "natural_exp":
        e = step / p["decay_steps"]
        if p["staircase"]:
            e = jnp.floor(e)
        lr = p["learning_rate"] * jnp.exp(-p["decay_rate"] * e)
    elif kind == "inverse_time":
        e = step / p["decay_steps"]
        if p["staircase"]:
            e = jnp.floor(e)
        lr = p["learning_rate"] / (1.0 + p["decay_rate"] * e)
    elif kind == "polynomial":
        if p["cycle"]:
            div = jnp.ceil(jnp.maximum(step, 1.0) / p["decay_steps"])
            decay_steps = p["decay_steps"] * div
        else:
            decay_steps = p["decay_steps"]
        gstep = jnp.minimum(step, decay_steps)
        lr = (p["learning_rate"] - p["end_learning_rate"]) * \
            (1 - gstep / decay_steps) ** p["power"] + p["end_learning_rate"]
    elif kind == "piecewise":
        bounds = jnp.asarray(p["boundaries"], jnp.float32)
        values = jnp.asarray(p["values"], jnp.float32)
        idx = jnp.sum((step >= bounds).astype(jnp.int32))
        lr = values[idx]
    elif kind == "cosine":
        epoch = jnp.floor(step / p["step_each_epoch"])
        lr = p["learning_rate"] / 2.0 * (
            jnp.cos(epoch * math.pi / p["epochs"]) + 1.0)
    elif kind == "linear_warmup":
        base = ins["BaseLr"][0].reshape(()) if ins.get("BaseLr") \
            else p["base_lr"]
        frac = jnp.clip(step / p["warmup_steps"], 0.0, 1.0)
        warm = p["start_lr"] + (p["end_lr"] - p["start_lr"]) * frac
        lr = jnp.where(step < p["warmup_steps"], warm, base)
    else:
        raise ValueError(f"unknown lr schedule {kind}")
    return {"Out": [lr.reshape((1,))]}


def _schedule(kind, extra_inputs=None, **params):
    helper = LayerHelper(f"lr_{kind}")
    step = _decay_step_counter()
    out = helper.create_variable_for_type_inference("float32")
    out.desc.stop_gradient = True
    ins = {"Step": [step]}
    if extra_inputs:
        ins.update(extra_inputs)
    helper.append_op(type="lr_schedule", inputs=ins,
                     outputs={"Out": [out]},
                     attrs=dict(params, kind=kind))
    return out


def noam_decay(d_model, warmup_steps):
    return _schedule("noam", d_model=d_model, warmup_steps=warmup_steps)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    return _schedule("exponential", learning_rate=learning_rate,
                     decay_steps=decay_steps, decay_rate=decay_rate,
                     staircase=staircase)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    return _schedule("natural_exp", learning_rate=learning_rate,
                     decay_steps=decay_steps, decay_rate=decay_rate,
                     staircase=staircase)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    return _schedule("inverse_time", learning_rate=learning_rate,
                     decay_steps=decay_steps, decay_rate=decay_rate,
                     staircase=staircase)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    return _schedule("polynomial", learning_rate=learning_rate,
                     decay_steps=decay_steps,
                     end_learning_rate=end_learning_rate, power=power,
                     cycle=cycle)


def piecewise_decay(boundaries, values):
    assert len(values) == len(boundaries) + 1
    return _schedule("piecewise", boundaries=[float(b) for b in boundaries],
                     values=[float(v) for v in values])


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return _schedule("cosine", learning_rate=learning_rate,
                     step_each_epoch=step_each_epoch, epochs=epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Ramp start_lr→end_lr over warmup_steps, then use learning_rate
    (scalar or schedule Variable), matching the reference
    learning_rate_scheduler.py linear_lr_warmup."""
    from ..core.program import Variable

    extra = None
    base_lr = 0.0
    if isinstance(learning_rate, Variable):
        extra = {"BaseLr": [learning_rate]}
    else:
        base_lr = float(learning_rate)
    return _schedule("linear_warmup", extra_inputs=extra,
                     warmup_steps=warmup_steps, start_lr=float(start_lr),
                     end_lr=float(end_lr), base_lr=base_lr)
