"""AsyncExecutor: file-shard training with a threaded host pipeline.

TPU-native analog of the reference AsyncExecutor
(reference: paddle/fluid/framework/async_executor.cc:72-234 — per-thread
ExecutorThreadWorker instances each parsing a file shard and running the
program op-by-op; python/paddle/fluid/async_executor.py wrapper).

Architecture shift: the reference parallelized *compute* across CPU
threads (one program replica per thread, shared params).  On TPU the
device serializes compute anyway, so the thread pool moves to where it
still matters — parsing file shards — and the single jitted train step
consumes a merged device-fed queue (data/pipeline.py DeviceFeeder).
Semantics match: shards are walked once per epoch, fetch vars report
periodically, and parsing overlaps device compute.

The Baidu-pslib distributed-KV path (async_executor.cc init_server/
init_worker) is obsolete on TPU: sharded embedding tables over the mesh
(parallel/, SparseGrad) replace the parameter server — documented
divergence, same capability.

Shard dispatch goes through a lease queue (data/task_queue.py — the
in-process analog of the Go master's task service,
go/master/service.go:106,341): a parser thread that dies or stalls
returns its shard for another worker, with at-least-once re-delivery
and max_failures retirement.  Multi-host dispatch (the Go master served
leases over RPC to many trainers) is a documented non-goal: synchronous
SPMD steps over jax.distributed make per-host dataset partitioning
static (dist.py shard_filelist-by-process) rather than work-stolen.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.executor import Executor, Scope, global_scope, scope_guard
from .core.program import Program
from .data.data_feed import DataFeedDesc, MultiSlotDataFeed
from .data.pipeline import DeviceFeeder


class AsyncExecutor:
    """reference: python/paddle/fluid/async_executor.py AsyncExecutor."""

    def __init__(self, place=None, run_mode: str = ""):
        self.place = place
        self._exe = Executor(place)

    def run(self, program: Program, data_feed: DataFeedDesc,
            filelist: Sequence[str], thread_num: Optional[int] = None,
            fetch: Sequence = (), mode: str = "", debug: bool = False,
            scope: Optional[Scope] = None,
            report_every: int = 100,
            shard_lease_timeout: float = 300.0,
            shard_max_failures: int = 3) -> Dict[str, float]:
        """Train over `filelist` once.  thread_num parser threads split
        the shards (reference async_executor.cc: files round-robin over
        threads; default FLAGS.paddle_num_threads); fetch vars are
        averaged and (debug=True) printed every `report_every` steps.
        Returns {fetch_name: mean_over_run}.
        """
        if thread_num is None:
            from .flags import FLAGS

            thread_num = int(FLAGS.paddle_num_threads)
        if thread_num < 1:
            raise ValueError("thread_num must be >= 1")
        if not filelist:
            raise ValueError("empty filelist")
        feed_parser = MultiSlotDataFeed(data_feed)
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch]

        import queue as queue_mod
        import threading
        import time as time_mod

        from .data.decorator import _ReaderError
        from .data.task_queue import TaskQueue

        # Shards dispatch through a lease queue instead of a static
        # round-robin split (reference analog: the Go master's task
        # service, go/master/service.go:106,341): a parser thread that
        # dies or stalls past its lease returns its shard for another
        # worker, so one bad thread no longer strands a slice of the
        # dataset.  Delivery is AT-LEAST-ONCE — a retried shard can
        # re-emit batches that already reached the device queue.
        n_workers = min(thread_num, len(filelist))
        tq = TaskQueue(list(filelist), lease_timeout=shard_lease_timeout,
                       max_failures=shard_max_failures)

        merged: "queue_mod.Queue" = queue_mod.Queue(maxsize=4 * n_workers)
        _STOP = object()
        abort = threading.Event()

        _LOST = object()

        def _put(item, keepalive=None):
            """Returns True when enqueued, False when aborting, _LOST
            when the keepalive reports the lease is gone.  keepalive
            runs every wait iteration so consumer BACKPRESSURE (full
            queue during a long compile/step) keeps the lease alive —
            lease time measures a dead parser, not a slow consumer."""
            while not abort.is_set():
                if keepalive is not None and not keepalive():
                    return _LOST
                try:
                    merged.put(item, timeout=0.1)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def worker(widx):
            try:
                while not abort.is_set():
                    task = tq.acquire(f"parser-{widx}")
                    if task is None:
                        if tq.all_done():
                            break
                        time_mod.sleep(0.02)
                        continue
                    try:
                        lost = False
                        keepalive = (lambda t=task:
                                     tq.renew(t.task_id, t.lease))
                        for batch in feed_parser.batches([task.shard]):
                            r = _put(batch, keepalive=keepalive)
                            if r is _LOST:
                                lost = True  # re-leased elsewhere
                                break
                            if r is False:
                                tq.fail(task.task_id, task.lease)
                                return
                            # heartbeat per batch too (fast consumers
                            # never hit the _put wait loop)
                            if not tq.renew(task.task_id, task.lease):
                                lost = True
                                break
                        if not lost:
                            tq.complete(task.task_id, task.lease)
                    except BaseException as e:  # noqa: BLE001
                        if not tq.fail(task.task_id, task.lease):
                            # retired after max_failures: surface on the
                            # consumer (reference: ExecutorThreadWorker
                            # aborts on reader errors) — never silently
                            # truncate the dataset
                            _put(_ReaderError(e))
                            return
                _put(_STOP)
            except BaseException as e:  # noqa: BLE001
                _put(_ReaderError(e))

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(n_workers)]
        for t in threads:
            t.start()

        def reader():
            # termination must NOT require a _STOP from every worker: a
            # truly stalled thread never sends one (its shard re-leases
            # to others) — exit once the queue has drained after every
            # shard completed or retired; retired shards raise in the
            # end-of-run failed_tasks() check even if their in-flight
            # _ReaderError loses this race
            done = 0
            while done < len(threads):
                try:
                    item = merged.get(timeout=0.2)
                except queue_mod.Empty:
                    if tq.all_done() and merged.empty():
                        return
                    continue
                if item is _STOP:
                    done += 1
                    continue
                if isinstance(item, _ReaderError):
                    raise RuntimeError(
                        "async_executor shard reader failed"
                    ) from item.error
                yield item

        feeder = DeviceFeeder(reader, capacity=4)
        totals = {n: 0.0 for n in fetch_names}
        steps = 0
        target_scope = scope or global_scope()
        try:
            with scope_guard(target_scope):
                for feed in feeder:
                    vals = self._exe.run(program, feed=feed,
                                         fetch_list=list(fetch_names))
                    steps += 1
                    for n, v in zip(fetch_names, vals):
                        totals[n] += float(np.asarray(v).reshape(-1)[0])
                    if debug and steps % report_every == 0:
                        stats = ", ".join(
                            f"{n}={totals[n] / steps:.6f}"
                            for n in fetch_names)
                        print(f"[async_executor] step {steps}: {stats}")
        finally:
            # on any consumer-side exit, unblock and reap BOTH sides:
            # parser threads parked on merged.put (abort flag + drain)
            # AND the DeviceFeeder producer parked on merged.get (one
            # _STOP per worker completes reader()'s done-count)
            abort.set()
            try:
                while True:
                    merged.get_nowait()
            except queue_mod.Empty:
                pass
            for _ in threads:
                try:
                    merged.put_nowait(_STOP)
                except queue_mod.Full:
                    break
            feeder.reset()
            for t in threads:
                t.join(timeout=5)
        dead = tq.failed_tasks()
        if dead:
            raise RuntimeError(
                "async_executor: shards retired after "
                f"{tq.max_failures} failed leases (data NOT fully "
                f"consumed): {[t.shard for t in dead]}")
        if steps == 0:
            raise RuntimeError(
                "no batches produced — check filelist contents and the "
                "DataFeedDesc batch_size vs shard sizes")
        return {n: totals[n] / steps for n in fetch_names}
