"""Serving telemetry, wired into the paddle_tpu.observe pillars.

What a serving operator needs to see, and where it comes from:

- **latency percentiles** — p50/p95/p99 of per-request end-to-end time
  (submit → future resolved) and of per-batch executable time.  Both
  use `observe.LatencyHistogram` (log-spaced bins, no sample storage).
  Convention note: on the test/TPU tunnel every dispatch pays ~114 ms
  RTT, so `exec_ms` is dominated by the tunnel at low occupancy — the
  batch AMORTIZES that cost over its members, which is exactly the
  quantity `exec_per_req_ms` reports (the dispatch-amortized compute
  latency of docs/SERVING.md).
- **occupancy + padding waste** — real requests per bucket slot, and
  the fraction of padded elements that carried no data (batch padding
  + ragged seq padding).  Low occupancy means max_wait_ms is too
  short or traffic too thin; high waste means the bucket ladder is too
  coarse.
- **robustness counters** — shed (queue-full fast rejects), deadline
  misses (dropped before dispatch), bucket misses.
- **compile hygiene** — XLA compiles after warmup, from
  `observe.runtime_stats` (pillar 2).  Steady-state serving must hold
  this at ZERO; any nonzero value is a shape leak and is emitted as a
  loud `serving_compile_post_warmup` event.

Snapshots are emitted as structured `serving_window` events through
`observe.RunEventLog` (pillar 3) every `window` completed requests and
at drain, carrying run-id/git-sha provenance like every other artifact
in the repo.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..observe.events import RunEventLog
from ..observe.monitoring import LatencyHistogram, runtime_stats


class ServingStats:
    """Thread-safe serving counters + histograms + event emission."""

    def __init__(self, event_log: Optional[RunEventLog] = None,
                 window: int = 256):
        if window < 1:
            raise ValueError("window must be >= 1")
        self._lock = threading.Lock()
        self._event_log = event_log
        self.window = int(window)
        self.e2e_ms = LatencyHistogram()
        self.exec_ms = LatencyHistogram()
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.deadline_misses = 0
        self.bucket_misses = 0
        self.executor_failures = 0   # failed dispatches (batches)
        self.circuit_rejects = 0     # fast-rejects while DEGRADED
        self.batches = 0
        self._slots = 0           # sum of bucket batch sizes dispatched
        self._real = 0            # sum of real requests dispatched
        self._elems_real = 0.0    # element-level fill (ragged-aware)
        self._elems_padded = 0.0
        self.max_queue_depth = 0
        self.reloads = 0             # hot weight swaps applied
        self.reload_pause_ms = 0.0   # worst single swap pause
        self.warmup: Dict[str, Any] = {}
        self._rt_base: Optional[Dict[str, Any]] = None
        self._merged_compiles = 0  # post-warmup compiles folded in by
        #                            merge() from other replicas' stats
        self._emitted_at = 0      # completed count at last window emit
        self._compiles_reported = 0

    # -- recording ------------------------------------------------------
    def record_warmup(self, n_buckets: int, compiles: int,
                      compile_s: float, seconds: float):
        with self._lock:
            self.warmup = {"buckets": n_buckets, "compiles": compiles,
                           "compile_s": round(compile_s, 3),
                           "seconds": round(seconds, 3)}
            # post-warmup compile accounting starts here
            self._rt_base = runtime_stats.snapshot()
        self._emit("serving_warmup", **self.warmup)

    def record_submit(self, queue_depth: int):
        with self._lock:
            self.submitted += 1
            if queue_depth > self.max_queue_depth:
                self.max_queue_depth = queue_depth

    def record_shed(self):
        with self._lock:
            self.shed += 1

    def record_deadline_miss(self):
        with self._lock:
            self.deadline_misses += 1

    def record_bucket_miss(self):
        with self._lock:
            self.bucket_misses += 1

    def record_executor_failure(self):
        with self._lock:
            self.executor_failures += 1

    def record_circuit_reject(self):
        with self._lock:
            self.circuit_rejects += 1

    def record_batch(self, n_real: int, bucket_batch: int,
                     elems_real: float, elems_padded: float,
                     exec_ms: float):
        with self._lock:
            self.batches += 1
            self._real += n_real
            self._slots += bucket_batch
            self._elems_real += elems_real
            self._elems_padded += elems_padded
        self.exec_ms.record(exec_ms)

    def record_done(self, e2e_ms: float):
        self.e2e_ms.record(e2e_ms)
        with self._lock:
            self.completed += 1

    def record_reload(self, pause_ms: float):
        with self._lock:
            self.reloads += 1
            if pause_ms > self.reload_pause_ms:
                self.reload_pause_ms = float(pause_ms)

    # -- reading --------------------------------------------------------
    def post_warmup_compiles(self) -> int:
        """XLA backend compiles since warmup finished (must stay 0 in
        steady state — the zero-recompile serving contract), plus any
        folded in by merge() from other replicas."""
        base = 0 if self._rt_base is None \
            else runtime_stats.delta(self._rt_base)["compiles"]
        return base + self._merged_compiles

    def reset_compile_base(self):
        """Restart the post-warmup compile window NOW.  The fleet start
        path needs this: runtime_stats is process-global, so replica
        K's warmup compiles would otherwise land inside replica 0's
        post-warmup window and break the zero-compile contract for a
        fleet that never leaked a shape."""
        with self._lock:
            self._rt_base = runtime_stats.snapshot()
            self._merged_compiles = 0
            self._compiles_reported = 0

    def merge(self, other: "ServingStats") -> "ServingStats":
        """Fold another replica's counters and histograms into this one
        IN PLACE (and return self) — the fleet aggregation surface.
        Histograms merge exactly (LatencyHistogram.merge: bin-wise
        addition, config mismatch rejected); counters sum; gauges
        (max_queue_depth, reload_pause_ms) take the max.  Mixing stats
        classes (DecodeStats into ServingStats) is rejected — their
        snapshots answer different questions."""
        if type(other) is not type(self):
            raise TypeError(
                f"cannot merge {type(other).__name__} into "
                f"{type(self).__name__} (config mismatch)")
        # histograms first: a bin-config mismatch must reject BEFORE
        # any counter has been folded
        self.e2e_ms.merge(other.e2e_ms)
        self.exec_ms.merge(other.exec_ms)
        with other._lock:
            o = {f: getattr(other, f) for f in (
                "submitted", "completed", "shed", "deadline_misses",
                "bucket_misses", "executor_failures", "circuit_rejects",
                "batches", "reloads", "_slots", "_real", "_elems_real",
                "_elems_padded")}
            o_depth = other.max_queue_depth
            o_pause = other.reload_pause_ms
        o_compiles = other.post_warmup_compiles()
        with self._lock:
            for f, v in o.items():
                setattr(self, f, getattr(self, f) + v)
            if o_depth > self.max_queue_depth:
                self.max_queue_depth = o_depth
            if o_pause > self.reload_pause_ms:
                self.reload_pause_ms = o_pause
            self._merged_compiles += o_compiles
        return self

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "deadline_misses": self.deadline_misses,
                "bucket_misses": self.bucket_misses,
                "executor_failures": self.executor_failures,
                "circuit_rejects": self.circuit_rejects,
                "batches": self.batches,
                "max_queue_depth": self.max_queue_depth,
                "reloads": self.reloads,
                "reload_pause_ms": round(self.reload_pause_ms, 3),
                "batch_occupancy": round(self._real / self._slots, 4)
                if self._slots else None,
                "padding_waste": round(
                    1.0 - self._elems_real / self._elems_padded, 4)
                if self._elems_padded else None,
            }
            if self.warmup:
                out["warmup"] = dict(self.warmup)
        e2e = self.e2e_ms.summary()
        ex = self.exec_ms.summary()
        out["e2e_ms"] = e2e
        out["exec_ms"] = ex
        # dispatch-amortized compute latency: total executable time
        # spread over the requests it served
        out["exec_per_req_ms"] = (round(ex["sum_ms"] / out["completed"], 3)
                                  if out["completed"] else None)
        out["post_warmup_compiles"] = self.post_warmup_compiles()
        return out

    # -- emission (observe pillar 3) ------------------------------------
    def maybe_emit(self):
        """Emit a serving_window event every `window` completed
        requests, plus a loud event the first time a post-warmup
        compile is observed (a shape leaked past the buckets)."""
        emit_window = False
        with self._lock:
            if self.completed - self._emitted_at >= self.window:
                self._emitted_at = self.completed
                emit_window = True
        compiles = self.post_warmup_compiles()
        if compiles > self._compiles_reported:
            self._compiles_reported = compiles
            self._emit("serving_compile_post_warmup",
                       post_warmup_compiles=compiles)
        if emit_window:
            self.emit()

    def emit(self, kind: str = "serving_window", **extra: Any):
        snap = self.snapshot()
        snap.update(extra)
        self._emit(kind, **snap)
        return snap

    def _emit(self, kind: str, **fields: Any):
        if self._event_log is not None:
            self._event_log.event(kind, **fields)


class DecodeStats:
    """Telemetry for the continuous-batching decode engine (ISSUE 12).

    What a decode operator needs beyond the single-shot serving stats:

    - **TTFT vs TPOT** — time-to-first-token (submit → the prefill that
      produced the request's first token) and time-per-output-token
      (decode-chunk wall time amortized over the tokens it produced),
      as separate LatencyHistograms.  Both merge-compatible
      (`LatencyHistogram.merge`) so multi-engine windows aggregate
      exactly.  The ~114 ms tunnel RTT convention applies to TTFT the
      same way it does to e2e_ms: on the tunnel, TTFT is RTT-dominated
      and `tpot_ms` (chunked, dispatch-amortized) is the
      compute-honest number.
    - **iteration-level occupancy** — active slots per decode
      iteration over the slot budget; low occupancy means admission is
      starved (queue empty or pool dry), the continuous-batching
      analog of batch_occupancy.
    - **KV page-pool utilization** — allocated pages over the pool,
      sampled at every dispatch (mean + peak): the pool-sizing signal.
    - **preemptions** — slots evicted (pages reclaimed) because the
      pool ran dry; their requests requeue and regenerate.
    - **compile hygiene** — post-warmup compiles must stay ZERO across
      any join/leave/preempt pattern (fixed-shape executables), same
      contract and accounting as ServingStats.

    Snapshots emit as `serving_decode_window` events every `window`
    completed requests and at drain.
    """

    def __init__(self, event_log: Optional[RunEventLog] = None,
                 window: int = 64):
        if window < 1:
            raise ValueError("window must be >= 1")
        self._lock = threading.Lock()
        self._event_log = event_log
        self.window = int(window)
        self.ttft_ms = LatencyHistogram()
        self.tpot_ms = LatencyHistogram()
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.deadline_misses = 0
        self.bucket_misses = 0
        self.circuit_rejects = 0
        self.executor_failures = 0
        self.preemptions = 0
        self.evacuations = 0        # requests pulled off this replica
        #                             (scheduler death / weight roll)
        self.reloads = 0            # hot weight swaps applied
        self.reload_pause_ms = 0.0  # worst single swap pause
        self.prefills = 0           # prefill dispatches
        self.prefill_joins = 0      # requests admitted via those
        self.imports = 0            # KV-page handoff imports accepted
        #                             (role="decode" workers only)
        self.decode_dispatches = 0  # chunked decode dispatches
        self.decode_iterations = 0  # While iterations across them
        self.tokens_generated = 0
        self._slot_steps = 0.0      # sum(active_slots * iterations)
        self._cap_steps = 0.0       # sum(num_slots * iterations)
        self._util_sum = 0.0        # allocated/pool, per dispatch
        self._util_samples = 0
        self.peak_pages_in_use = 0
        # speculative decoding (ISSUE 20): sized by
        # configure_speculation(k); accept_hist bin a = verify rounds
        # in which a slot had exactly a drafts accepted (k+1 bins)
        self.spec_k = 0
        self.accept_hist: list = []
        self.verify_dispatches = 0  # speculative verify dispatches
        self.drafted_tokens = 0     # proposals scored (post-cap)
        self.accepted_tokens = 0    # proposals accepted
        self.spec_emitted_tokens = 0  # tokens committed by verifies
        self.warmup: Dict[str, Any] = {}
        self._rt_base: Optional[Dict[str, Any]] = None
        self._merged_compiles = 0
        self._emitted_at = 0
        self._compiles_reported = 0

    # -- recording ------------------------------------------------------
    def record_warmup(self, executables: int, compiles: int,
                      compile_s: float, seconds: float):
        with self._lock:
            self.warmup = {"executables": executables,
                           "compiles": compiles,
                           "compile_s": round(compile_s, 3),
                           "seconds": round(seconds, 3)}
            self._rt_base = runtime_stats.snapshot()
        self._emit("serving_decode_warmup", **self.warmup)

    def record_submit(self):
        with self._lock:
            self.submitted += 1

    def record_shed(self):
        with self._lock:
            self.shed += 1

    def record_deadline_miss(self):
        with self._lock:
            self.deadline_misses += 1

    def record_bucket_miss(self):
        with self._lock:
            self.bucket_misses += 1

    def record_circuit_reject(self):
        with self._lock:
            self.circuit_rejects += 1

    def record_executor_failure(self):
        with self._lock:
            self.executor_failures += 1

    def record_preemption(self, n: int = 1):
        with self._lock:
            self.preemptions += n

    def record_evacuation(self, n: int = 1):
        with self._lock:
            self.evacuations += n

    def record_reload(self, pause_ms: float):
        with self._lock:
            self.reloads += 1
            if pause_ms > self.reload_pause_ms:
                self.reload_pause_ms = float(pause_ms)

    def record_prefill(self, joins: int, ttfts_ms) -> None:
        with self._lock:
            self.prefills += 1
            self.prefill_joins += joins
            # each join's prefill produced that request's FIRST token
            self.tokens_generated += joins
        for ms in ttfts_ms:
            self.ttft_ms.record(ms)

    def record_import(self, n: int = 1):
        """A decode-role worker accepted a KV-page handoff (the first
        token was produced — and counted — on the PREFILL worker, so
        imports add no tokens here; the fleet-merged totals stay
        exact)."""
        with self._lock:
            self.imports += n

    def configure_speculation(self, k: int):
        """Size the accepted-token histogram for speculate_k = k
        (called once by the engine before any verify records)."""
        if int(k) < 1:
            raise ValueError(f"speculate k must be >= 1, got {k}")
        with self._lock:
            if self.verify_dispatches:
                raise RuntimeError(
                    "configure_speculation after verifies recorded")
            self.spec_k = int(k)
            self.accept_hist = [0] * (self.spec_k + 1)

    def record_verify(self, drafted: int, emitted: int,
                      accept_counts) -> None:
        """One speculative verify dispatch: `drafted` proposals scored
        (sum of post-cap draft lengths), `emitted` tokens committed,
        and per-active-slot accepted counts (each 0..k) binned into
        the histogram."""
        with self._lock:
            if not self.spec_k:
                raise RuntimeError("record_verify before "
                                   "configure_speculation")
            counts = [int(a) for a in accept_counts]
            for a in counts:  # validate BEFORE mutating: a bad record
                if not 0 <= a <= self.spec_k:  # must not tear counters
                    raise ValueError(
                        f"accepted count {a} outside 0..{self.spec_k}")
            self.verify_dispatches += 1
            self.drafted_tokens += int(drafted)
            self.spec_emitted_tokens += int(emitted)
            for a in counts:
                self.accepted_tokens += a
                self.accept_hist[a] += 1

    def record_decode(self, iterations: int, active_slots: int,
                      num_slots: int, tokens: int, pages_in_use: int,
                      num_pages: int, elapsed_ms: float):
        with self._lock:
            self.decode_dispatches += 1
            self.decode_iterations += int(iterations)
            self.tokens_generated += int(tokens)
            self._slot_steps += float(active_slots) * iterations
            self._cap_steps += float(num_slots) * iterations
            self._util_sum += (pages_in_use / num_pages
                               if num_pages else 0.0)
            self._util_samples += 1
            if pages_in_use > self.peak_pages_in_use:
                self.peak_pages_in_use = int(pages_in_use)
        if tokens:
            # dispatch-amortized per-token latency (the tunnel RTT and
            # the chunk's While iterations spread over its tokens)
            self.tpot_ms.record(elapsed_ms / tokens)

    def record_done(self):
        with self._lock:
            self.completed += 1

    # -- reading --------------------------------------------------------
    def post_warmup_compiles(self) -> int:
        base = 0 if self._rt_base is None \
            else runtime_stats.delta(self._rt_base)["compiles"]
        return base + self._merged_compiles

    def reset_compile_base(self):
        """Restart the post-warmup compile window NOW (see
        ServingStats.reset_compile_base — the fleet start path)."""
        with self._lock:
            self._rt_base = runtime_stats.snapshot()
            self._merged_compiles = 0
            self._compiles_reported = 0

    def merge(self, other: "DecodeStats") -> "DecodeStats":
        """Fold another replica's decode telemetry into this one IN
        PLACE (and return self): TTFT/TPOT histograms merge exactly,
        counters sum, occupancy/utilization accumulators sum (the
        merged ratios stay exact weighted means), peaks take the max.
        Stats-class and histogram-bin config mismatches are rejected.
        Caveat shared with ServingStats.merge: runtime_stats compile
        counters are process-global, so N same-process replicas that
        each saw a post-warmup compile report it N times in the merged
        sum — an over-count in exactly the direction the zero-compile
        contract wants (0 stays 0; any leak reads louder, not
        quieter)."""
        if type(other) is not type(self):
            raise TypeError(
                f"cannot merge {type(other).__name__} into "
                f"{type(self).__name__} (config mismatch)")
        if other.spec_k and self.spec_k and other.spec_k != self.spec_k:
            raise ValueError(
                f"cannot merge speculation histograms with different k "
                f"({self.spec_k} vs {other.spec_k})")
        self.ttft_ms.merge(other.ttft_ms)
        self.tpot_ms.merge(other.tpot_ms)
        with other._lock:
            o = {f: getattr(other, f) for f in (
                "submitted", "completed", "shed", "deadline_misses",
                "bucket_misses", "circuit_rejects", "executor_failures",
                "preemptions", "evacuations", "reloads", "prefills",
                "prefill_joins", "imports", "decode_dispatches",
                "decode_iterations", "tokens_generated",
                "verify_dispatches", "drafted_tokens", "accepted_tokens",
                "spec_emitted_tokens", "_slot_steps",
                "_cap_steps", "_util_sum", "_util_samples")}
            o_peak = other.peak_pages_in_use
            o_pause = other.reload_pause_ms
            o_spec_k = other.spec_k
            o_hist = list(other.accept_hist)
        o_compiles = other.post_warmup_compiles()
        with self._lock:
            for f, v in o.items():
                setattr(self, f, getattr(self, f) + v)
            if o_peak > self.peak_pages_in_use:
                self.peak_pages_in_use = o_peak
            if o_pause > self.reload_pause_ms:
                self.reload_pause_ms = o_pause
            if o_spec_k:
                if not self.spec_k:  # adopt a speculating replica's k
                    self.spec_k = o_spec_k
                    self.accept_hist = [0] * (o_spec_k + 1)
                self.accept_hist = [a + b for a, b in
                                    zip(self.accept_hist, o_hist)]
            self._merged_compiles += o_compiles
        return self

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "deadline_misses": self.deadline_misses,
                "bucket_misses": self.bucket_misses,
                "circuit_rejects": self.circuit_rejects,
                "executor_failures": self.executor_failures,
                "preemptions": self.preemptions,
                "evacuations": self.evacuations,
                "reloads": self.reloads,
                "reload_pause_ms": round(self.reload_pause_ms, 3),
                "prefills": self.prefills,
                "prefill_joins": self.prefill_joins,
                "imports": self.imports,
                "decode_dispatches": self.decode_dispatches,
                "decode_iterations": self.decode_iterations,
                "tokens_generated": self.tokens_generated,
                "slot_occupancy": round(
                    self._slot_steps / self._cap_steps, 4)
                if self._cap_steps else None,
                "kv_page_utilization": round(
                    self._util_sum / self._util_samples, 4)
                if self._util_samples else None,
                "peak_pages_in_use": self.peak_pages_in_use,
            }
            if self.spec_k:
                out["speculation"] = {
                    "speculate_k": self.spec_k,
                    "verify_dispatches": self.verify_dispatches,
                    "drafted_tokens": self.drafted_tokens,
                    "accepted_tokens": self.accepted_tokens,
                    "emitted_tokens": self.spec_emitted_tokens,
                    "accept_rate": round(
                        self.accepted_tokens / self.drafted_tokens, 4)
                    if self.drafted_tokens else None,
                    "accept_hist": list(self.accept_hist),
                    # emitted tokens over the verify rows paid for
                    # (each slot-verify burns k+1 folded rows, and
                    # sum(accept_hist) counts slot-verifies): 1.0 means
                    # every row committed a token
                    "speculation_efficiency": round(
                        self.spec_emitted_tokens /
                        (sum(self.accept_hist) * (self.spec_k + 1)), 4)
                    if sum(self.accept_hist) else None,
                }
            if self.warmup:
                out["warmup"] = dict(self.warmup)
        out["ttft_ms"] = self.ttft_ms.summary()
        out["tpot_ms"] = self.tpot_ms.summary()
        out["post_warmup_compiles"] = self.post_warmup_compiles()
        return out

    # -- emission -------------------------------------------------------
    def maybe_emit(self):
        emit_window = False
        with self._lock:
            if self.completed - self._emitted_at >= self.window:
                self._emitted_at = self.completed
                emit_window = True
        compiles = self.post_warmup_compiles()
        if compiles > self._compiles_reported:
            self._compiles_reported = compiles
            self._emit("serving_compile_post_warmup",
                       post_warmup_compiles=compiles,
                       component="decode_engine")
        if emit_window:
            self.emit()

    def emit(self, kind: str = "serving_decode_window", **extra: Any):
        snap = self.snapshot()
        snap.update(extra)
        self._emit(kind, **snap)
        return snap

    def _emit(self, kind: str, **fields: Any):
        if self._event_log is not None:
            self._event_log.event(kind, **fields)
