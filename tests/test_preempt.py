"""Preemption-tolerant training (ISSUE 7): async checkpointing,
bit-exact resume, and crash chaos.

Fast (in-process) coverage:
- async saves stall the step loop only for the snapshot: an injected
  slow write (chaos delaypoint) does not block `_save_checkpoint`, and
  `ckpt_save` events record snapshot_ms vs write_ms separately,
- a second save submitted while one is writing waits — never
  interleaves/corrupts,
- a writer-thread failure (failpoint mid-write) surfaces as a
  structured CheckpointWriteError on the NEXT save, and the torn
  directory stays unloadable (manifest-last invariant, async edition),
- bit-exact resume: dropout RNG + Adam moments + dynamic loss-scale
  value/counters + guard skip counter all survive save→"kill"→resume,
  and the resumed trajectory is BIT-IDENTICAL to an uninterrupted one,
- resuming against a drifted unique_name build fails loudly
  (CheckpointStateMismatchError), newer train_state versions are
  rejected, drain via request_drain() writes the emergency checkpoint
  and raises TrainingPreempted with the distinct exit code.

Slow (real-subprocess) chaos — the acceptance proof:
- SIGKILL at a random step + relaunch → final params bit-identical to
  an uninterrupted control, zero loadable torn checkpoints,
- SIGTERM → drain → exit code PREEMPT_EXIT_CODE + ckpt_emergency event
  → relaunch → bit-identical.

`python tests/test_preempt.py --ci-smoke` runs the two subprocess
scenarios standalone (tools/run_ci.sh crash-resume smoke).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

# script mode (run_ci.sh crash-resume smoke runs this file directly):
# repo root on sys.path + CPU pin, neither needed under pytest/conftest
if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid
from paddle_tpu import layers, observe
from paddle_tpu.contrib import CheckpointConfig, Trainer
from paddle_tpu.contrib.trainer import TRAIN_STATE_VERSION
from paddle_tpu.resilience import PREEMPT_EXIT_CODE, chaos, preempt
from paddle_tpu.resilience import errors as resilience_errors

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "preempt_worker.py")
STEPS_PER_EPOCH = 12  # preempt_worker.BATCHES_PER_EPOCH
EPOCHS = 2


@pytest.fixture(autouse=True)
def _clear_chaos_and_drain():
    yield
    chaos.clear()
    preempt.clear_drain()
    preempt.uninstall_preempt_handler()


# ---------------------------------------------------------------------------
# In-process: the training job (mirrors preempt_worker, smaller)
# ---------------------------------------------------------------------------

def _train_func():
    x = layers.data(name="x", shape=[6], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=8, act="relu")
    h = layers.dropout(h, dropout_prob=0.3)
    pred = layers.fc(h, size=1)
    return layers.mean(layers.square_error_cost(pred, y))


def _opt_func():
    return fluid.amp.decorate(
        fluid.optimizer.Adam(learning_rate=0.01),
        use_dynamic_loss_scaling=True, init_loss_scaling=16.0,
        incr_every_n_steps=3)


def _reader(n=12, nan_at=4):
    from paddle_tpu.data import decorator

    def base():
        r = np.random.RandomState(5)
        for _ in range(n):
            yield {"x": r.rand(8, 6).astype(np.float32),
                   "y": r.rand(8, 1).astype(np.float32)}

    shuffled = decorator.shuffle(base, 4, seed=13)

    def read():
        for i, b in enumerate(shuffled()):
            yield (chaos.poison_feed(b, ["x"]) if i == nan_at else b)

    return read


def _persistables(t):
    return {v.name: np.asarray(t.scope.find_var(v.name))
            for v in t.train_program.list_vars() if v.persistable}


def _trainer(ckpt_dir, log=None, async_save=True, step_interval=3):
    tel = (observe.TelemetryConfig(interval=100, log_path=log)
           if log else None)
    return Trainer(_train_func, _opt_func,
                   checkpoint_config=CheckpointConfig(
                       ckpt_dir, step_interval=step_interval,
                       epoch_interval=10 ** 6, async_save=async_save),
                   telemetry=tel)


# ---------------------------------------------------------------------------
# Async checkpointing
# ---------------------------------------------------------------------------

def test_async_save_blocks_only_for_snapshot(tmp_path):
    """Acceptance: steps proceed while the background write is
    artificially slowed; the blocking (snapshot) portion is measured
    and reported separately from the write portion."""
    log = str(tmp_path / "ev.jsonl")
    t = _trainer(str(tmp_path / "ck"), log=log)
    chaos.arm_delay("ckpt:write", 0.5, times=10 ** 6)
    t0 = time.perf_counter()
    t.train(num_epochs=1, reader=_reader(6))  # 2 saves @ interval 3
    elapsed = time.perf_counter() - t0
    t.stop()
    saves = [e for e in observe.read_events(log)
             if e["event"] == "ckpt_save"]
    assert len(saves) == 2
    for e in saves:
        assert e["asynchronous"] is True
        assert e["write_ms"] >= 500, e  # the injected stall landed...
        assert e["snapshot_ms"] < 500, e  # ...in the write phase only
        assert e["bytes"] > 0
    # the step loop paid the snapshot (+ wait-for-previous), not the
    # two 0.5s writes back to back; generous bound for a loaded box
    assert t.ckpt_stats["saves"] == 2
    assert t.ckpt_stats["blocking_ms"] < 1000.0, t.ckpt_stats
    assert elapsed < 30, elapsed
    # and the final checkpoint is complete + loadable
    t2 = _trainer(str(tmp_path / "ck"), log=log)
    assert (t2._resume_epoch, t2._resume_step_in_epoch) == (0, 6)


def test_async_second_save_waits_never_corrupts(tmp_path):
    """Two saves in quick succession with a slowed writer: the second
    submit WAITS for the first write; both land complete and the
    newest is loadable with intact CRCs."""
    t = _trainer(str(tmp_path / "ck"))
    chaos.arm_delay("ckpt:write", 0.3, times=10 ** 6)
    t.train(num_epochs=1, reader=_reader(12))  # 4 saves, back to back
    t.stop()  # waits out the writer; surfaces any failure
    ids = t._list_checkpoints()
    assert len(ids) >= 2
    # every listed serial has manifest + trainer state and loads clean
    t2 = _trainer(str(tmp_path / "ck"))
    for serial in ids:
        path = os.path.join(str(tmp_path / "ck"), f"ckpt_{serial}")
        assert os.path.exists(os.path.join(path,
                                           fluid.io.SHARD_MANIFEST))
        st = t2._load_checkpoint(path)  # CRC-verified member reads
        assert st["serial"] == serial


def test_async_writer_failure_surfaces_on_next_save(tmp_path):
    """A writer-thread death mid-flush (failpoint between shard and
    manifest writes) must surface as a structured CheckpointWriteError
    on the NEXT save — and the torn dir must stay unloadable."""
    t = _trainer(str(tmp_path / "ck"))
    t.train(num_epochs=1, reader=_reader(3))  # serial 0 lands clean
    chaos.arm("ckpt:before_manifest")
    t._save_checkpoint(1, 0, 99)              # background write dies
    time.sleep(0.1)  # let the writer thread hit the failpoint
    with pytest.raises(resilience_errors.CheckpointWriteError) as ei:
        t._save_checkpoint(2, 0, 100)
    d = ei.value.as_dict()
    assert d["error"] == "checkpoint_write_failed"
    assert "ckpt:before_manifest" in str(d)
    torn = os.path.join(str(tmp_path / "ck"), "ckpt_1")
    assert os.path.isdir(torn)
    assert not os.path.exists(os.path.join(torn,
                                           fluid.io.SHARD_MANIFEST))
    # a restarted trainer never sees the torn serial
    t3 = _trainer(str(tmp_path / "ck"))
    assert 1 not in t3._list_checkpoints()


def test_trainer_train_end_surfaces_writer_failure(tmp_path):
    """The same failure at the END of training surfaces from train()
    itself (await-pending before returning green)."""
    t = _trainer(str(tmp_path / "ck"))
    chaos.arm("ckpt:before_manifest")
    with pytest.raises(resilience_errors.CheckpointWriteError):
        t.train(num_epochs=1, reader=_reader(3))


# ---------------------------------------------------------------------------
# Bit-exact resume (the PR-4 state that used to be silently dropped)
# ---------------------------------------------------------------------------

def _control_params(tmp_path):
    tc = _trainer(str(tmp_path / "ctl"), step_interval=100,
                  async_save=False)
    tc.train(num_epochs=1, reader=_reader(12))
    return _persistables(tc), tc


def test_bit_exact_resume_with_rng_adam_loss_scale(tmp_path):
    """Kill at step 6 (simulated: a 6-batch reader ends the run right
    after the step-6 save), resume with the full reader: final params
    must be BIT-identical to the uninterrupted control — proving RNG
    stream, Adam moments, and the loss-scale schedule all resumed."""
    ref, _tc = _control_params(tmp_path)

    tk = _trainer(str(tmp_path / "ck"))
    tk.train(num_epochs=1, reader=_reader(6))
    killed_tel = observe.fetch_telemetry(tk.scope, reset=False)
    killed_moments = {k: v for k, v in _persistables(tk).items()
                      if "moment" in k or "pow_acc" in k}
    tk.stop()

    tr = _trainer(str(tmp_path / "ck"))
    assert (tr._resume_epoch, tr._resume_step_in_epoch) == (0, 6)
    # PR-4 state restored at resume time, before any new step:
    resumed_tel = observe.fetch_telemetry(tr.scope, reset=False)
    # the schedule MOVED by kill time (16 → 32 after 3 calm steps →
    # 16 on the NaN), so equality here is not a vacuous init-vs-init
    assert resumed_tel.loss_scale == killed_tel.loss_scale
    assert resumed_tel.skipped_update_steps \
        == killed_tel.skipped_update_steps == 1
    for name, want in killed_moments.items():
        np.testing.assert_array_equal(
            np.asarray(tr.scope.find_var(name)), want, err_msg=name)

    tr.train(num_epochs=1, reader=_reader(12))
    got = _persistables(tr)
    assert set(got) == set(ref)
    for name, want in ref.items():
        assert got[name].dtype == want.dtype
        assert np.array_equal(got[name], want), \
            f"{name} diverged after resume"


def test_resume_restores_ls_counters_exactly(tmp_path):
    """The loss-scale good/bad counters (not just the scale value)
    survive: a resume mid-way through an incr_every_n_steps window must
    not restart the window (that would double the calm-step wait)."""
    tk = _trainer(str(tmp_path / "ck"))
    tk.train(num_epochs=1, reader=_reader(6))
    from paddle_tpu.observe.metrics import TELEMETRY_VAR

    raw = {k: int(np.asarray(v)) if np.asarray(v).dtype.kind == "i"
           else float(np.asarray(v))
           for k, v in tk.scope.find_var(TELEMETRY_VAR).items()}
    tk.stop()
    tr = _trainer(str(tmp_path / "ck"))
    raw2 = {k: int(np.asarray(v)) if np.asarray(v).dtype.kind == "i"
            else float(np.asarray(v))
            for k, v in tr.scope.find_var(TELEMETRY_VAR).items()}
    for k in ("loss_scale", "ls_good_steps", "ls_bad_steps",
              "skipped_update_steps"):
        assert raw2[k] == raw[k], (k, raw, raw2)
    # the schedule moved off init in the killed run, so this is not a
    # vacuous all-zeros comparison
    assert raw["ls_good_steps"] > 0 or raw["ls_bad_steps"] > 0


def test_resume_without_unique_name_guard_fails_loudly(tmp_path):
    """Regression (satellite): a resuming build whose unique_name
    counters drifted must raise CheckpointStateMismatchError — never
    silently bind saved arrays to wrong variables.  Drift is simulated
    by tampering the recorded counters (equivalently: the build ran
    outside unique_name.guard() after other programs polluted the
    global generator)."""
    t = _trainer(str(tmp_path / "ck"))
    t.train(num_epochs=1, reader=_reader(3))
    t.stop()
    sp = os.path.join(str(tmp_path / "ck"), "ckpt_0",
                      "__trainer_state__.json")
    with open(sp) as f:
        st = json.load(f)
    ids = st["train_state"]["unique_name_ids"]
    ids["fc"] = ids.get("fc", 0) + 7  # drifted counter
    with open(sp, "w") as f:
        json.dump(st, f)
    with pytest.raises(
            resilience_errors.CheckpointStateMismatchError) as ei:
        _trainer(str(tmp_path / "ck"))
    d = ei.value.as_dict()
    assert d["error"] == "checkpoint_state_mismatch"
    assert "fc" in d["drifted_keys"]

    # and at the io layer: a program REALLY built without the guard
    # (second build in-process -> drifted generated names) fails the
    # load with a structured missing-variable error, not a mis-bind
    def build(guarded):
        import contextlib

        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        guard = (fluid.unique_name.guard() if guarded
                 else contextlib.nullcontext())
        with guard, fluid.program_guard(main, startup), \
                fluid.scope_guard(scope):
            x = layers.data(name="x", shape=[4], dtype="float32")
            pred = layers.fc(x, size=1)
            loss = layers.mean(pred)
            fluid.optimizer.Adam(learning_rate=0.1).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
        return main, scope, exe

    main1, scope1, exe1 = build(guarded=True)
    d1 = str(tmp_path / "io_ck")
    with fluid.scope_guard(scope1):
        fluid.io.save_sharded(exe1, d1, main_program=main1)
    # the unguarded rebuild inherits a polluted GLOBAL generator (any
    # earlier in-process program build leaves counters behind — here
    # made explicit), so every generated name drifts
    for _ in range(3):
        fluid.unique_name.generate("fc")
    main2, scope2, exe2 = build(guarded=False)  # names drift here
    with pytest.raises(resilience_errors.CheckpointIncompleteError):
        with fluid.scope_guard(scope2):
            fluid.io.load_sharded(exe2, d1, main_program=main2)


def test_newer_train_state_version_rejected(tmp_path):
    t = _trainer(str(tmp_path / "ck"))
    t.train(num_epochs=1, reader=_reader(3))
    t.stop()
    sp = os.path.join(str(tmp_path / "ck"), "ckpt_0",
                      "__trainer_state__.json")
    with open(sp) as f:
        st = json.load(f)
    st["train_state"]["version"] = TRAIN_STATE_VERSION + 1
    with open(sp, "w") as f:
        json.dump(st, f)
    t2 = _trainer(str(tmp_path / "ck"))
    with pytest.raises(resilience_errors.CheckpointFormatError):
        t2._load_checkpoint(os.path.join(str(tmp_path / "ck"),
                                         "ckpt_0"))


# ---------------------------------------------------------------------------
# Drain (in-process)
# ---------------------------------------------------------------------------

def test_request_drain_writes_emergency_ckpt_and_raises(tmp_path):
    log = str(tmp_path / "ev.jsonl")
    t = _trainer(str(tmp_path / "ck"), log=log)

    def handler(e):
        from paddle_tpu.contrib.trainer import EndStepEvent

        if isinstance(e, EndStepEvent) and e.step == 3:
            preempt.request_drain("test-preemption")

    with pytest.raises(resilience_errors.TrainingPreempted) as ei:
        t.train(num_epochs=1, reader=_reader(12),
                event_handler=handler)
    assert ei.value.exit_code == PREEMPT_EXIT_CODE
    d = ei.value.as_dict()
    assert d["reason"] == "test-preemption"
    # the in-flight step FINISHED before the drain: cursor is step 4
    assert (d["epoch"], d["step"]) == (0, 4)
    events = observe.read_events(log)
    kinds = [e["event"] for e in events]
    assert "preempt_drain" in kinds
    assert "ckpt_emergency" in kinds
    em = [e for e in events if e["event"] == "ckpt_emergency"][-1]
    assert em["serial"] == d["serial"]
    # the drain request was CONSUMED by the drain (the flag is
    # process-global): an in-process resumed train() must run to
    # completion, not instantly re-drain on the stale flag
    assert not preempt.drain_requested()
    # auto-resume picks the emergency checkpoint up
    t2 = _trainer(str(tmp_path / "ck"), log=log)
    assert (t2._resume_epoch, t2._resume_step_in_epoch) == (0, 4)
    t2.train(num_epochs=1, reader=_reader(12))  # completes, no drain
    t2.stop()


def test_sigterm_handler_sets_drain_flag():
    installed = preempt.install_preempt_handler()
    assert installed  # pytest runs tests on the main thread
    assert not preempt.drain_requested()
    os.kill(os.getpid(), signal.SIGTERM)
    # CPython delivers the signal at the next bytecode boundary
    deadline = time.monotonic() + 5
    while not preempt.drain_requested():
        assert time.monotonic() < deadline
        time.sleep(0.01)
    assert preempt.drain_reason() == "signal:SIGTERM"


# ---------------------------------------------------------------------------
# Cross-process crash chaos (the acceptance proof; slow)
# ---------------------------------------------------------------------------

def _worker_cmd(ckpt, out, log, slow_write_ms=120.0):
    return [sys.executable, WORKER, "--ckpt", ckpt, "--out", out,
            "--log", log, "--epochs", str(EPOCHS),
            "--slow-write-ms", str(slow_write_ms)]


def _worker_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker pins cpu via jax.config
    return env


def _run_to_done(ckpt, out, log, timeout=300, **kw):
    err_path = out + ".stderr"
    with open(err_path, "w") as ef:
        r = subprocess.run(_worker_cmd(ckpt, out, log, **kw),
                          stdout=subprocess.PIPE, stderr=ef,
                          text=True, env=_worker_env(),
                          timeout=timeout)
    assert r.returncode == 0 and "DONE" in r.stdout, \
        f"worker rc={r.returncode}\n{r.stdout}\n" \
        + open(err_path).read()[-3000:]
    return r.stdout


def _run_until_step(ckpt, out, log, target_global_step, sig,
                    timeout=300, **kw):
    """Launch the worker, watch STEP lines, send `sig` the moment the
    target step completes.  Returns (returncode, stdout_so_far+rest)."""
    err_path = out + f".stderr.{int(sig)}"
    ef = open(err_path, "w")
    p = subprocess.Popen(_worker_cmd(ckpt, out, log, **kw),
                         stdout=subprocess.PIPE, stderr=ef,
                         text=True, env=_worker_env())
    lines = []
    try:
        deadline = time.monotonic() + timeout
        for line in p.stdout:
            lines.append(line)
            if line.startswith("STEP "):
                _, e, s = line.split()
                if int(e) * STEPS_PER_EPOCH + int(s) \
                        >= target_global_step:
                    p.send_signal(sig)
                    break
            if time.monotonic() > deadline:
                p.kill()
                raise AssertionError(
                    "worker never reached step "
                    f"{target_global_step}: {''.join(lines)}")
        rest = p.stdout.read()
        rc = p.wait(timeout=60)
    finally:
        ef.close()
    return rc, "".join(lines) + (rest or "")


def _assert_zero_loadable_torn(ckpt_dir):
    """Every torn directory (killed mid-save) must be invisible to the
    resume walk: a dir missing the trainer-state file is by definition
    not listed, and a dir missing the shard manifest must not carry a
    trainer-state file at all (state is written strictly last)."""
    if not os.path.isdir(ckpt_dir):
        return 0  # killed before the first save — fresh-start resume
    torn = 0
    for name in sorted(os.listdir(ckpt_dir)):
        path = os.path.join(ckpt_dir, name)
        if not (name.startswith("ckpt_") and os.path.isdir(path)):
            continue
        has_manifest = os.path.exists(
            os.path.join(path, fluid.io.SHARD_MANIFEST))
        has_state = os.path.exists(
            os.path.join(path, "__trainer_state__.json"))
        if has_state:
            assert has_manifest, \
                f"{name}: trainer state without manifest — the " \
                f"write-order invariant broke (state must be LAST)"
        else:
            torn += 1
    return torn


def _compare_final_params(out_a, out_b):
    a, b = np.load(out_a), np.load(out_b)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        assert a[k].dtype == b[k].dtype
        assert np.array_equal(a[k], b[k]), \
            f"{k} NOT bit-identical after crash-resume"


def _random_kill_step():
    # an ARBITRARY step (acceptance wording) — anywhere in the first
    # 3/4 of the run so the relaunch has work left; logged on failure
    import random

    return random.Random(os.urandom(8)).randrange(
        2, (EPOCHS * STEPS_PER_EPOCH * 3) // 4)


def run_sigkill_chaos(tmp_path):
    ctl_out = os.path.join(tmp_path, "ctl.npz")
    _run_to_done(os.path.join(tmp_path, "ctl_ck"), ctl_out,
                 os.path.join(tmp_path, "ctl.jsonl"))

    ck = os.path.join(tmp_path, "victim_ck")
    vic_out = os.path.join(tmp_path, "victim.npz")
    log = os.path.join(tmp_path, "victim.jsonl")
    kill_at = _random_kill_step()
    rc, out = _run_until_step(ck, vic_out, log, kill_at,
                              signal.SIGKILL)
    assert rc == -signal.SIGKILL, (kill_at, rc, out)
    assert not os.path.exists(vic_out)  # it really died mid-run
    torn = _assert_zero_loadable_torn(ck)
    # relaunch: auto-resume must complete and match the control
    out2 = _run_to_done(ck, vic_out, log)
    assert "DONE" in out2
    _compare_final_params(ctl_out, vic_out)
    return {"kill_at_global_step": kill_at, "torn_dirs": torn}


def run_sigterm_drain_chaos(tmp_path):
    ctl_out = os.path.join(tmp_path, "ctl2.npz")
    _run_to_done(os.path.join(tmp_path, "ctl2_ck"), ctl_out,
                 os.path.join(tmp_path, "ctl2.jsonl"))

    ck = os.path.join(tmp_path, "drain_ck")
    vic_out = os.path.join(tmp_path, "drain.npz")
    log = os.path.join(tmp_path, "drain.jsonl")
    term_at = _random_kill_step()
    rc, out = _run_until_step(ck, vic_out, log, term_at,
                              signal.SIGTERM)
    # the DISTINCT drained-exit code — not 143 (raw SIGTERM death)
    assert rc == PREEMPT_EXIT_CODE, (term_at, rc, out)
    assert "PREEMPTED" in out
    events = observe.read_events(log)
    kinds = [e["event"] for e in events]
    assert "preempt_drain" in kinds, kinds
    assert "ckpt_emergency" in kinds, kinds
    drain = [e for e in events if e["event"] == "preempt_drain"][-1]
    assert drain["reason"] == "signal:SIGTERM"
    out2 = _run_to_done(ck, vic_out, log)
    assert "DONE" in out2
    _compare_final_params(ctl_out, vic_out)
    return {"term_at_global_step": term_at}


@pytest.mark.slow
def test_sigkill_chaos_bit_exact_resume(tmp_path):
    info = run_sigkill_chaos(str(tmp_path))
    print("sigkill chaos:", info)


@pytest.mark.slow
def test_sigterm_drain_distinct_exit_and_bit_exact(tmp_path):
    info = run_sigterm_drain_chaos(str(tmp_path))
    print("sigterm drain chaos:", info)


if __name__ == "__main__":
    # run_ci.sh crash-resume smoke: both chaos scenarios, no pytest
    import argparse
    import tempfile

    ap = argparse.ArgumentParser()
    ap.add_argument("--ci-smoke", action="store_true")
    if not ap.parse_args().ci_smoke:
        sys.exit("usage: python tests/test_preempt.py --ci-smoke")
    d = tempfile.mkdtemp(prefix="preempt_smoke_")
    info = run_sigkill_chaos(d)
    info2 = run_sigterm_drain_chaos(d)
    print("crash-resume smoke OK:", {**info, **info2})
