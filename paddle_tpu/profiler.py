"""Profiling.

reference: python/paddle/fluid/profiler.py:221 profiler context manager +
platform/profiler.h RecordEvent ranges + CUPTI DeviceTracer →
chrome-trace (SURVEY.md §5.1).  TPU equivalent: jax.profiler traces
(XPlane/Perfetto, viewable in TensorBoard or ui.perfetto.dev) with the
same op-name annotation convention via TraceAnnotation.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: str = "/tmp/profile"):
    """Drop-in for fluid.profiler.profiler: captures a device+host trace
    for the enclosed region.  `state`/`sorted_key` are accepted for API
    parity; the trace contains both host and device activity."""
    import jax

    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def record_event(name: str):
    """RecordEvent RAII range (platform/profiler.h:72): annotates the
    enclosed host region; annotations flow into device traces."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def start_profiler(state: str = "All",
                   profile_path: str = "/tmp/profile"):
    import jax

    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: str = "/tmp/profile"):
    import jax

    jax.profiler.stop_trace()


def cuda_profiler(*args, **kwargs):
    raise NotImplementedError(
        "cuda_profiler is CUDA-specific; use profiler()/record_event, "
        "which capture TPU device traces")


class Timer:
    """Host-side timer (platform/timer.h) for benchmark reporting."""

    def __init__(self):
        self._start = None
        self.elapsed = 0.0

    def start(self):
        self._start = time.perf_counter()

    def pause(self):
        if self._start is not None:
            self.elapsed += time.perf_counter() - self._start
            self._start = None

    def reset(self):
        self._start = None
        self.elapsed = 0.0
