"""Benchmark harness — prints ONE JSON line with the headline metric.

reference: benchmark/fluid/fluid_benchmark.py (imgs/sec reporting with
--use_fake_data).  Headline: ResNet-50 ImageNet training imgs/sec/chip
(BASELINE.json metric).  vs_baseline compares against the reference's
only published ResNet-50 training number (81.69 img/s, MKL-DNN Xeon 6148,
benchmark/IntelOptimizedPaddle.md:40-45).

Run on the real TPU chip: `python bench.py [--model resnet50|transformer]
[--batch N] [--steps N]`.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _timed_loop(exe, program, feed_dev, loss, steps, warmup):
    """Device-resident fake-data loop (reference --use_fake_data):
    feeds are placed on device once; timed steps run fetch-free so the
    chip chains steps without host round-trips (the tunnel in this
    environment has high host<->device latency); one final fetch
    synchronizes and validates the loss."""
    for _ in range(warmup):
        exe.run(program, feed=feed_dev, fetch_list=[loss])
    # compile the K-iteration fused step, then time it: the host
    # dispatches ONCE and the chip chains `steps` training steps
    exe.run(program, feed=feed_dev, fetch_list=[loss], iterations=steps)
    t0 = time.perf_counter()
    (lv,) = exe.run(program, feed=feed_dev, fetch_list=[loss],
                    iterations=steps)
    elapsed = time.perf_counter() - t0
    return elapsed, float(np.asarray(lv).reshape(-1)[0])


def bench_resnet50(batch_size: int, steps: int, warmup: int):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = resnet.build_model(dataset="flowers", depth=50,
                                   class_dim=1000, learning_rate=0.1)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {
            "data": jax.device_put(
                rng.rand(batch_size, 3, 224, 224).astype(np.float32)),
            "label": jnp.asarray(rng.randint(0, 1000, (batch_size, 1)),
                                 dtype=jnp.int64),
        }
        elapsed, last_loss = _timed_loop(exe, main, feed, model["loss"],
                                         steps, warmup)
    imgs_per_sec = batch_size * steps / elapsed
    return {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec",
        "vs_baseline": round(imgs_per_sec / 81.69, 3),
        "detail": {"batch_size": batch_size, "steps": steps,
                   "last_loss": last_loss},
    }


def bench_transformer(batch_size: int, steps: int, warmup: int,
                      max_length: int = 256):
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    import jax.numpy as jnp

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = transformer.build_model(
            src_vocab_size=32000, trg_vocab_size=32000,
            max_length=max_length, n_layer=6, n_head=8, d_model=512,
            d_inner_hid=2048, dropout=0.1)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {k: jnp.asarray(v) for k, v in
                transformer.make_fake_batch(batch_size, max_length,
                                            32000, 32000).items()}
        elapsed, last_loss = _timed_loop(exe, main, feed, model["loss"],
                                         steps, warmup)
    tokens_per_sec = batch_size * max_length * steps / elapsed
    return {
        "metric": "transformer_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,  # no reference-published transformer number
        "detail": {"batch_size": batch_size, "max_length": max_length,
                   "steps": steps, "last_loss": last_loss},
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "transformer"])
    p.add_argument("--batch", type=int, default=0)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    args = p.parse_args()

    if args.model == "resnet50":
        batch = args.batch or 128
        result = bench_resnet50(batch, args.steps, args.warmup)
    else:
        batch = args.batch or 32
        result = bench_transformer(batch, args.steps, args.warmup)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
