"""QuantizeTranspiler: QAT program rewrite.

TPU-native analog of the reference QAT transpiler
(reference: python/paddle/fluid/contrib/quantize/quantize_transpiler.py:1
— rewrites the program to insert fake_quantize ops on the inputs of
quantizable ops (conv2d, depthwise_conv2d, mul) and fake_dequantize after
them, with per-var dedup and scale state).

Here the rewrite inserts the combined quantize-dequantize simulation op
in front of each quantizable input (weights use dynamic abs-max,
activations use a moving-average scale held in persistable state), and
rewires the consumer to the simulated tensor.  Gradients flow by the
straight-through estimator inside the op impl (ops/quantize.py), so no
grad-op surgery is needed — jax AD differentiates the rewritten program
as-is.  Run it BEFORE append_backward/minimize, like the reference's
training_transpile is run on the un-differentiated program.
"""

from __future__ import annotations

from typing import Dict, Optional

from .core import unique_name
from .core.desc import OpDesc
from .core.program import Operator, Program, default_main_program
from .initializer import Constant

QUANTIZABLE_OPS = {"conv2d", "depthwise_conv2d", "mul", "matmul"}
# slot holding the weight operand per op type (quantized with abs_max)
_WEIGHT_SLOTS = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                 "mul": "Y", "matmul": "Y"}


class QuantizeTranspiler:
    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "moving_average_abs_max",
                 weight_quantize_type: str = "abs_max",
                 moving_rate: float = 0.9):
        if activation_quantize_type not in ("abs_max",
                                            "moving_average_abs_max"):
            raise ValueError(
                f"unsupported activation_quantize_type "
                f"{activation_quantize_type!r}")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.moving_rate = moving_rate

    # -- public API (reference quantize_transpiler.py API) ---------------
    def training_transpile(self, program: Optional[Program] = None,
                           startup_program: Optional[Program] = None):
        from .core.program import default_startup_program

        program = program or default_main_program()
        if startup_program is None:
            # moving-average scale state must get its init op somewhere —
            # the reference-compatible no-arg call uses the default
            # startup program
            startup_program = default_startup_program()
        if program._backward_info is not None:
            raise RuntimeError(
                "QuantizeTranspiler must run before append_backward/"
                "minimize (the reference transpiles the forward program)")
        self._rewrite(program, startup_program, is_test=False)
        return program

    def inference_transpile(self, program: Optional[Program] = None):
        """Rewrite a test/inference program: same graph, is_test scales
        (moving-average state is read, not updated)."""
        program = program or default_main_program()
        self._rewrite(program, None, is_test=True)
        return program

    # -- rewrite ---------------------------------------------------------
    def _rewrite(self, program: Program, startup_program, is_test: bool):
        block = program.global_block()
        # (src var name, is_weight) -> simulated var name
        quantized: Dict[tuple, str] = {}
        new_ops = []
        for op in block.ops:
            if op.desc.type in QUANTIZABLE_OPS:
                weight_slot = _WEIGHT_SLOTS[op.desc.type]
                for slot, names in op.desc.inputs.items():
                    rewired = []
                    for name in names:
                        var = block.var(name)
                        is_weight = (slot == weight_slot
                                     or getattr(var, "trainable", False))
                        key = (name, is_weight)
                        if key not in quantized:
                            qname, q_ops = self._make_qdq(
                                block, program, startup_program, name,
                                is_weight, is_test)
                            new_ops.extend(q_ops)
                            quantized[key] = qname
                        rewired.append(quantized[key])
                    op.desc.inputs[slot] = rewired
            new_ops.append(op)
        block.ops = new_ops
        program._bump()

    def _make_qdq(self, block, program, startup_program, name: str,
                  is_weight: bool, is_test: bool):
        src = block.var(name)
        qvar = block.create_var(
            name=unique_name.generate(f"{name}.quantized"),
            shape=src.shape, dtype=src.dtype)
        bits = self.weight_bits if is_weight else self.activation_bits
        use_moving = (not is_weight
                      and self.act_type == "moving_average_abs_max")
        if use_moving:
            state_name = f"{name}.quant_scale_state"
            if not block.has_var(state_name):
                block.create_var(name=state_name, shape=(1,),
                                 dtype="float32", persistable=True,
                                 stop_gradient=True)
                if startup_program is not None:
                    sb = startup_program.global_block()
                    if not sb.has_var(state_name):
                        sp = sb.create_var(name=state_name, shape=(1,),
                                           dtype="float32",
                                           persistable=True,
                                           stop_gradient=True)
                        Constant(0.0)(sp, sb)
            desc = OpDesc(
                type="fake_quantize_dequantize_moving_average_abs_max",
                inputs={"X": [name], "InScale": [state_name]},
                outputs={"Out": [qvar.name], "OutScale": [state_name]},
                attrs={"bit_length": bits, "moving_rate": self.moving_rate,
                       "is_test": is_test})
        else:
            scale_out = block.create_var(
                name=unique_name.generate(f"{name}.scale"),
                shape=(1,), dtype="float32", stop_gradient=True)
            desc = OpDesc(
                type="fake_quantize_dequantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qvar.name], "OutScale": [scale_out.name]},
                attrs={"bit_length": bits})
        return qvar.name, [Operator(block, desc)]
