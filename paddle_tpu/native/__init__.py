"""Native (C++) components, loaded via ctypes.

The reference keeps its record IO, readers, and executors in C++
(reference: paddle/fluid/recordio/*.cc, operators/reader/*.cc); here the
hot codec lives in recordio.cc and binds through the C ABI — no pybind
dependency (ctypes per the environment's binding guidance).  Missing
toolchain or failed build degrade gracefully to the pure-python
implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "librecordio.so")
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["sh", os.path.join(_DIR, "build.sh")],
                       check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def recordio_lib() -> Optional[ctypes.CDLL]:
    """The native codec, built on first use; None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH):
        src = os.path.join(_DIR, "recordio.cc")
        if not os.path.exists(src) or not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.rio_encode_bound.restype = ctypes.c_longlong
    lib.rio_encode_bound.argtypes = [ctypes.c_longlong, ctypes.c_int]
    lib.rio_encode_chunk.restype = ctypes.c_longlong
    lib.rio_encode_chunk.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.c_longlong]
    lib.rio_decode_chunk.restype = ctypes.c_int
    lib.rio_decode_chunk.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char_p,
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_int)]
    _lib = lib
    return _lib
