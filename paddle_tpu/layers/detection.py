"""Detection layers.

reference: python/paddle/fluid/layers/detection.py:1 (1812 LoC) — the
starter set: prior_box, box_coder, iou_similarity, multiclass_nms,
yolov3_loss, plus ssd-style helpers.  Ops in ops/detection.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..layer_helper import LayerHelper


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """reference layers/detection.py prior_box."""
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance),
               "flip": bool(flip), "clip": bool(clip),
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": float(offset)})
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    """reference layers/detection.py box_coder."""
    helper = LayerHelper("box_coder", name=name)
    output = helper.create_variable_for_type_inference(target_box.dtype)
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    helper.append_op(type="box_coder", inputs=ins,
                     outputs={"OutputBox": [output]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return output


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    output = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [output]},
                     attrs={"box_normalized": box_normalized})
    return output


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  ap_version="integral", name=None):
    """reference layers/detection.py detection_map — in-graph per-batch
    mAP (padded-dense contract; cross-batch accumulation lives in
    metrics.DetectionMAP, see ops/detection.py)."""
    helper = LayerHelper("detection_map", name=name)
    map_out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="detection_map",
        inputs={"DetectRes": [detect_res], "Label": [label]},
        outputs={"MAP": [map_out]},
        attrs={"class_num": class_num,
               "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version})
    return map_out


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """reference layers/detection.py multiclass_nms.  Static-shape
    contract: Out is (N, keep_top_k, 6) padded with -1 rows; the second
    return is the per-image valid count (replaces the LoD)."""
    helper = LayerHelper("multiclass_nms", name=name)
    output = helper.create_variable_for_type_inference(bboxes.dtype)
    rois_num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [output], "NmsRoisNum": [rois_num]},
        attrs={"score_threshold": float(score_threshold),
               "nms_top_k": int(nms_top_k),
               "keep_top_k": int(keep_top_k),
               "nms_threshold": float(nms_threshold),
               "nms_eta": float(nms_eta),
               "background_label": int(background_label)})
    return output, rois_num


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, name=None):
    """reference layers/detection.py yolov3_loss."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="yolov3_loss",
        inputs={"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]},
        outputs={"Loss": [loss]},
        attrs={"anchors": [int(a) for a in anchors],
               "anchor_mask": [int(m) for m in anchor_mask],
               "class_num": int(class_num),
               "ignore_thresh": float(ignore_thresh),
               "downsample_ratio": int(downsample_ratio)})
    return loss


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, loc_loss_weight=1.0, conf_loss_weight=1.0,
             name=None):
    """Simplified SSD matching loss (reference layers/detection.py
    ssd_loss — bipartite + per-prediction matching with hard negative
    mining).  Composed from iou_similarity/box_coder + standard losses at
    the layer level; per-prior assignment is best-IoU with threshold
    (the per-prediction half of the reference's strategy)."""
    from . import nn as nn_layers
    from . import ops as ops_layers
    from . import tensor as tensor_layers

    # iou: (num_gt, num_prior); each prior matches its best gt
    iou = iou_similarity(gt_box, prior_box)
    best_gt = tensor_layers.argmax(iou, axis=0)          # (P,)
    best_iou = nn_layers.reduce_max(iou, dim=0)          # (P,)
    matched = tensor_layers.cast(
        nn_layers.greater_equal(
            best_iou, tensor_layers.fill_constant(
                [1], "float32", overlap_threshold)), "float32")

    # localization targets: encode each prior's matched gt against the
    # prior (center-size form — the 1:1 case of box_coder, written
    # elementwise because the op broadcasts all gt×prior pairs)
    gt_sel = nn_layers.gather(gt_box, best_gt)       # (P, 4)

    def _corners(v):
        return tuple(
            nn_layers.reshape(
                nn_layers.slice(v, axes=[1], starts=[i], ends=[i + 1]),
                [-1])
            for i in range(4))

    px1, py1, px2, py2 = _corners(prior_box)
    gx1, gy1, gx2, gy2 = _corners(gt_sel)
    pw = nn_layers.elementwise_sub(px2, px1)
    ph = nn_layers.elementwise_sub(py2, py1)
    pcx = nn_layers.elementwise_add(px1, nn_layers.scale(pw, 0.5))
    pcy = nn_layers.elementwise_add(py1, nn_layers.scale(ph, 0.5))
    gw = nn_layers.elementwise_sub(gx2, gx1)
    gh = nn_layers.elementwise_sub(gy2, gy1)
    gcx = nn_layers.elementwise_add(gx1, nn_layers.scale(gw, 0.5))
    gcy = nn_layers.elementwise_add(gy1, nn_layers.scale(gh, 0.5))
    ox = nn_layers.elementwise_div(
        nn_layers.elementwise_sub(gcx, pcx), pw)
    oy = nn_layers.elementwise_div(
        nn_layers.elementwise_sub(gcy, pcy), ph)
    ow = ops_layers.log(nn_layers.elementwise_div(gw, pw))
    oh = ops_layers.log(nn_layers.elementwise_div(gh, ph))
    target = tensor_layers.concat(
        [nn_layers.reshape(v, [-1, 1]) for v in (ox, oy, ow, oh)], axis=1)
    if prior_box_var is not None:
        # encode with the prior variances so box_coder's decode (which
        # multiplies by them) is the exact inverse at inference
        target = nn_layers.elementwise_div(target, prior_box_var)

    loc_l = nn_layers.reduce_sum(
        ops_layers.abs(nn_layers.elementwise_sub(location, target)), dim=1)
    loc_loss = nn_layers.reduce_sum(
        nn_layers.elementwise_mul(loc_l, matched))

    # confidence: matched priors take their gt's label, rest background
    lab_sel = tensor_layers.cast(
        nn_layers.gather(nn_layers.reshape(gt_label, [-1, 1]),
                             best_gt), "float32")
    bg = tensor_layers.fill_constant_batch_size_like(
        matched, [-1], "float32", float(background_label))
    one = tensor_layers.fill_constant_batch_size_like(
        matched, [-1], "float32", 1.0)
    labels = tensor_layers.cast(
        nn_layers.elementwise_add(
            nn_layers.elementwise_mul(
                nn_layers.reshape(lab_sel, [-1]), matched),
            nn_layers.elementwise_mul(
                bg, nn_layers.elementwise_sub(one, matched))), "int64")
    conf_l = nn_layers.reshape(nn_layers.softmax_with_cross_entropy(
        confidence, nn_layers.reshape(labels, [-1, 1])), [-1])
    # negative balancing: scale unmatched-prior losses so their expected
    # total is neg_pos_ratio × the positive count (a soft version of the
    # reference's hard-negative mining — top-k selection needs a dynamic
    # k that XLA's static shapes preclude; weighting preserves the same
    # positive/negative loss balance in expectation)
    num_pos = nn_layers.reduce_sum(matched)
    num_neg = nn_layers.elementwise_sub(
        tensor_layers.fill_constant([1], "float32",
                                    float(matched.shape[0])), num_pos)
    neg_w = nn_layers.elementwise_min(
        tensor_layers.fill_constant([1], "float32", 1.0),
        nn_layers.elementwise_div(
            nn_layers.scale(num_pos, scale=float(neg_pos_ratio)),
            nn_layers.elementwise_max(
                num_neg, tensor_layers.fill_constant([1], "float32",
                                                     1.0))))
    weights = nn_layers.elementwise_add(
        matched, nn_layers.elementwise_mul(
            nn_layers.elementwise_sub(one, matched),
            nn_layers.reshape(neg_w, [1])))
    conf_loss = nn_layers.reduce_sum(
        nn_layers.elementwise_mul(conf_l, weights))
    return nn_layers.elementwise_add(
        nn_layers.scale(loc_loss, scale=loc_loss_weight),
        nn_layers.scale(conf_loss, scale=conf_loss_weight))


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """reference layers/detection.py anchor_generator."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": [float(s) for s in anchor_sizes],
               "aspect_ratios": [float(r) for r in aspect_ratios],
               "variances": list(variance),
               "stride": [float(s) for s in stride],
               "offset": float(offset)})
    return anchors, var


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, name=None):
    """reference layers/detection.py density_prior_box."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"densities": [int(d) for d in densities],
               "fixed_sizes": [float(s) for s in fixed_sizes],
               "fixed_ratios": [float(r) for r in fixed_ratios],
               "variances": list(variance), "clip": bool(clip),
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": float(offset)})
    return boxes, var


def box_clip(input, im_info=None, im_shape=None, name=None):
    """reference layers/detection.py box_clip."""
    helper = LayerHelper("box_clip", name=name)
    output = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": [input]}
    attrs = {}
    if im_info is not None:
        ins["ImInfo"] = [im_info]
    elif im_shape is not None:
        attrs["im_shape"] = [int(s) for s in im_shape]
    else:
        raise ValueError("box_clip needs im_info or im_shape")
    helper.append_op(type="box_clip", inputs=ins,
                     outputs={"Output": [output]}, attrs=attrs)
    return output


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """reference layers/detection.py bipartite_match."""
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_variable_for_type_inference("int32")
    match_dist = helper.create_variable_for_type_inference(
        dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type,
               "dist_threshold": float(dist_threshold)})
    return match_indices, match_dist


def target_assign(input, matched_indices, mismatch_value=0, name=None):
    """reference layers/detection.py target_assign."""
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """reference layers/detection.py generate_proposals; static-shape
    contract: (N, post_nms_top_n, 4) zero-padded + valid counts."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    rois_num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoisNum": [rois_num]},
        attrs={"pre_nms_topN": int(pre_nms_top_n),
               "post_nms_topN": int(post_nms_top_n),
               "nms_thresh": float(nms_thresh),
               "min_size": float(min_size),
               "eta": float(eta)})
    return rois, rois_num


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      gt_num=None, name=None):
    """RPN training-target assignment (reference layers/detection.py
    rpn_target_assign:54): samples fg/bg anchors, gathers the matching
    predictions, and returns
    (predicted_scores, predicted_location, target_label, target_bbox,
    bbox_inside_weight, score_weight).

    bbox_pred (N, A, 4), cls_logits (N, A, 1); gt_boxes (N, G, 4)
    zero-padded with `gt_num` valid counts (static-shape analog of the
    reference's LoD gt input); the extra score_weight return (absent in
    the reference, which emitted variable-length rows) masks padded
    sample slots and anchor_var is accepted for API parity."""
    from . import nn as nn_layers

    helper = LayerHelper("rpn_target_assign", name=name)
    loc_index = helper.create_variable_for_type_inference("int32")
    tgt_bbox = helper.create_variable_for_type_inference(bbox_pred.dtype)
    in_w = helper.create_variable_for_type_inference(bbox_pred.dtype)
    score_index = helper.create_variable_for_type_inference("int32")
    tgt_lbl = helper.create_variable_for_type_inference("int32")
    score_w = helper.create_variable_for_type_inference("float32")
    fg_num = helper.create_variable_for_type_inference("int32")
    ins = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
           "ImInfo": [im_info]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    if gt_num is not None:
        ins["GtNum"] = [gt_num]
    helper.append_op(
        type="rpn_target_assign", inputs=ins,
        outputs={"LocationIndex": [loc_index], "TargetBBox": [tgt_bbox],
                 "BBoxInsideWeight": [in_w], "ScoreIndex": [score_index],
                 "TargetLabel": [tgt_lbl], "ScoreWeight": [score_w],
                 "ForegroundNumber": [fg_num]},
        attrs={"rpn_batch_size_per_im": int(rpn_batch_size_per_im),
               "rpn_straddle_thresh": float(rpn_straddle_thresh),
               "rpn_fg_fraction": float(rpn_fg_fraction),
               "rpn_positive_overlap": float(rpn_positive_overlap),
               "rpn_negative_overlap": float(rpn_negative_overlap),
               "use_random": bool(use_random)})
    # gather predictions at the sampled anchor slots (reference gathers
    # on the flattened pred tensors)
    pred_loc = nn_layers.batched_gather(bbox_pred, loc_index)
    pred_score = nn_layers.batched_gather(cls_logits, score_index)
    return (pred_score, pred_loc, tgt_lbl, tgt_bbox, in_w, score_w)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             rpn_rois_num=None, gt_num=None, name=None):
    """Fast-RCNN head sampling (reference layers/detection.py
    generate_proposal_labels:1648).  Returns (rois, labels_int32,
    bbox_targets, bbox_inside_weights, bbox_outside_weights, rois_num);
    all (N, B, ...) fixed-slot tensors with rois_num active counts."""
    helper = LayerHelper("generate_proposal_labels", name=name)
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference("int32")
    tgts = helper.create_variable_for_type_inference(rpn_rois.dtype)
    in_w = helper.create_variable_for_type_inference(rpn_rois.dtype)
    out_w = helper.create_variable_for_type_inference(rpn_rois.dtype)
    rois_num = helper.create_variable_for_type_inference("int32")
    ins = {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
           "GtBoxes": [gt_boxes], "ImInfo": [im_info]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    if rpn_rois_num is not None:
        ins["RpnRoisNum"] = [rpn_rois_num]
    if gt_num is not None:
        ins["GtNum"] = [gt_num]
    helper.append_op(
        type="generate_proposal_labels", inputs=ins,
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [tgts], "BboxInsideWeights": [in_w],
                 "BboxOutsideWeights": [out_w], "RoisNum": [rois_num]},
        attrs={"batch_size_per_im": int(batch_size_per_im),
               "fg_fraction": float(fg_fraction),
               "fg_thresh": float(fg_thresh),
               "bg_thresh_hi": float(bg_thresh_hi),
               "bg_thresh_lo": float(bg_thresh_lo),
               "bbox_reg_weights": [float(v) for v in bbox_reg_weights],
               "class_nums": int(class_nums or 81),
               "use_random": bool(use_random)})
    return rois, labels, tgts, in_w, out_w, rois_num


def mine_hard_examples(cls_loss, loc_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative",
                       name=None):
    """Hard-negative mining (reference detection/
    mine_hard_examples_op.cc).  Returns (neg_indices (N, P) padded -1,
    neg_mask (N, P), updated_match_indices)."""
    helper = LayerHelper("mine_hard_examples", name=name)
    neg_idx = helper.create_variable_for_type_inference("int32")
    neg_mask = helper.create_variable_for_type_inference("float32")
    updated = helper.create_variable_for_type_inference("int32")
    ins = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
           "MatchDist": [match_dist]}
    if loc_loss is not None:
        ins["LocLoss"] = [loc_loss]
    helper.append_op(
        type="mine_hard_examples", inputs=ins,
        outputs={"NegIndices": [neg_idx], "NegMask": [neg_mask],
                 "UpdatedMatchIndices": [updated]},
        attrs={"neg_pos_ratio": float(neg_pos_ratio),
               "neg_dist_threshold": float(neg_dist_threshold),
               "sample_size": int(sample_size),
               "mining_type": mining_type})
    return neg_idx, neg_mask, updated


def polygon_box_transform(input, name=None):
    """EAST quad-geometry decode (reference layers/detection.py
    polygon_box_transform)."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="polygon_box_transform",
                     inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    """Perspective-warp quad ROIs to a fixed grid (reference
    layers/detection.py roi_perspective_transform); rois (R, 9) with a
    leading batch index."""
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="roi_perspective_transform",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"transformed_height": int(transformed_height),
               "transformed_width": int(transformed_width),
               "spatial_scale": float(spatial_scale)})
    return out
