"""Lease-based shard dispatch for data workers.

TPU-native analog of the reference's Go master task service
(reference: go/master/service.go:106 partition() splitting RecordIO
chunks into tasks, :341 the todo/pending/done queues with lease
timeouts — a task leased to a worker that never reports back re-queues
for another worker; repeated failures retire the task).

Here the queue is in-process (threaded parser workers share one
process; multi-host data dispatch rides jax.distributed instead of a
Go RPC master — divergence note in async_executor.py): workers acquire
shard leases, renew by finishing, and a worker that dies or stalls past
its lease returns the shard to the todo queue.  Delivery is
AT-LEAST-ONCE like the reference master: a retried shard may re-emit
batches already consumed.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class Task:
    task_id: int
    shard: object
    failures: int = 0
    lease_deadline: float = 0.0
    worker: Optional[str] = None
    lease: int = 0          # token: identifies WHICH lease is current


@dataclass
class _State:
    todo: List[Task] = field(default_factory=list)
    pending: Dict[int, Task] = field(default_factory=dict)
    done: List[Task] = field(default_factory=list)
    dead: List[Task] = field(default_factory=list)


class TaskQueue:
    """Thread-safe shard lease queue.

    acquire(worker) -> Task or None (None = nothing to hand out right
    now; poll again until all_done).  complete(task_id) retires a task;
    fail(task_id) (or lease expiry) re-queues it until max_failures,
    after which the task is dead and `failed_tasks` reports it —
    callers must surface that rather than silently dropping data
    (reference service.go:341 moves a task failing too often to the
    failed list)."""

    def __init__(self, shards, lease_timeout: float = 60.0,
                 max_failures: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.lease_timeout = float(lease_timeout)
        self.max_failures = int(max_failures)
        self._lock = threading.Lock()
        self._s = _State(todo=[Task(i, s) for i, s in enumerate(shards)])

    # -- internals (call with lock held) --------------------------------
    def _reap_expired(self):
        now = self._clock()
        expired = [t for t in self._s.pending.values()
                   if t.lease_deadline <= now]
        for t in expired:
            del self._s.pending[t.task_id]
            self._fail_locked(t)

    def _fail_locked(self, t: Task):
        t.failures += 1
        t.worker = None
        if t.failures >= self.max_failures:
            self._s.dead.append(t)
        else:
            self._s.todo.append(t)

    # -- worker API -----------------------------------------------------
    def acquire(self, worker: str = "") -> Optional[Task]:
        """Returns a SNAPSHOT of the leased task — the lease token must
        not change under the worker when the queue re-issues the task
        to someone else after expiry."""
        import dataclasses

        with self._lock:
            self._reap_expired()
            if not self._s.todo:
                return None
            t = self._s.todo.pop(0)
            t.worker = worker
            t.lease += 1
            t.lease_deadline = self._clock() + self.lease_timeout
            self._s.pending[t.task_id] = t
            return dataclasses.replace(t)

    def _current(self, task_id: int, lease: int) -> Optional[Task]:
        """The pending task iff `lease` is still the CURRENT lease —
        a worker whose lease expired and was re-issued must not affect
        the new owner's lease (its reports are stale)."""
        t = self._s.pending.get(task_id)
        return t if t is not None and t.lease == lease else None

    def renew(self, task_id: int, lease: int) -> bool:
        """Heartbeat: extend a live lease (workers renew per emitted
        batch, so lease time measures parser PROGRESS, not consumer
        backpressure).  False = the lease was lost (expired/re-issued);
        the worker should stop emitting from this shard."""
        with self._lock:
            t = self._current(task_id, lease)
            if t is None:
                return False
            t.lease_deadline = self._clock() + self.lease_timeout
            return True

    def complete(self, task_id: int, lease: int):
        with self._lock:
            t = self._current(task_id, lease)
            if t is not None:
                del self._s.pending[task_id]
                self._s.done.append(t)

    def fail(self, task_id: int, lease: int) -> bool:
        """Report a failed lease; returns True when the task will be
        retried (or the report was stale — someone else owns the task
        now), False when the task is retired as dead."""
        with self._lock:
            t = self._current(task_id, lease)
            if t is None:
                return True  # stale report: not this worker's problem
            del self._s.pending[task_id]
            self._fail_locked(t)
            return t.failures < self.max_failures

    # -- observers ------------------------------------------------------
    def all_done(self) -> bool:
        with self._lock:
            self._reap_expired()
            return not self._s.todo and not self._s.pending

    def failed_tasks(self) -> List[Task]:
        with self._lock:
            return list(self._s.dead)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            self._reap_expired()
            return {"todo": len(self._s.todo),
                    "pending": len(self._s.pending),
                    "done": len(self._s.done),
                    "dead": len(self._s.dead)}
