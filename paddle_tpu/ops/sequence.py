"""Sequence ops over padded (N, T, ...) batches with explicit lengths.

reference: paddle/fluid/operators/sequence_ops/ (46 files) — seq_pool,
seq_softmax, seq_expand, seq_pad/unpad, seq_mask, seq_reverse, seq_conv,
seq_concat, seq_slice, seq_enumerate + math/sequence_pooling etc.

The reference stores ragged batches as LoD (concatenated rows + offset
table, lod_tensor.h:38-58).  The TPU-native representation is padded
dense (N, T, ...) plus an int32 `SeqLen` (N,) — static shapes for XLA,
masking instead of offset iteration (SURVEY.md §5.7).  Ops accept SeqLen
as an optional input; without it the full padded length is used.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first, opt_in, out


def _mask(x_len, t, dtype=jnp.float32):
    """(N, T) validity mask from lengths."""
    return (jnp.arange(t)[None, :] < x_len[:, None]).astype(dtype)


def _reject_nested(ins, op_name):
    """Kernels without nested (LoD level-2) support must fail loudly
    rather than silently applying level-1 semantics to the sub-sequence
    axis (only sequence_pool removes a nesting level)."""
    if ins.get("SeqLen2"):
        raise NotImplementedError(
            f"{op_name} does not support nested (lod_level=2) inputs; "
            f"pool the inner level first (sequence_pool)")


@register_op("sequence_pool")
def sequence_pool(ctx, ins, attrs):
    x = first(ins, "X")  # (N, T, D...)
    seq_len = opt_in(ins, "SeqLen")
    seq_len2 = opt_in(ins, "SeqLen2")
    pool = attrs.get("pooltype", "AVERAGE").upper()
    if seq_len2 is not None:
        # nested (LoD level-2) input (B, S1, S2, D...): pooling removes
        # the INNERMOST level (reference sequence_pooling over the last
        # LoD level) → (B, S1, D...) with the level-1 lengths surviving
        # as the output's .seq_len (handled by the layer)
        b, s1 = x.shape[0], x.shape[1]
        flat = x.reshape((b * s1,) + x.shape[2:])
        sub = {"X": [flat], "SeqLen": [seq_len2.reshape(-1)]}
        inner = sequence_pool(ctx, sub, attrs)
        return {"Out": [inner["Out"][0].reshape((b, s1) +
                                                inner["Out"][0].shape[1:])],
                "MaxIndex": [jnp.zeros((b,), jnp.int32)]}
    n, t = x.shape[0], x.shape[1]
    if seq_len is None:
        seq_len = jnp.full((n,), t, jnp.int32)
    m = _mask(seq_len, t, x.dtype).reshape((n, t) + (1,) * (x.ndim - 2))
    lens = jnp.maximum(seq_len, 1).astype(x.dtype).reshape(
        (n,) + (1,) * (x.ndim - 2))
    if pool == "SUM":
        o = jnp.sum(x * m, axis=1)
    elif pool == "AVERAGE":
        o = jnp.sum(x * m, axis=1) / lens
    elif pool == "SQRT":
        o = jnp.sum(x * m, axis=1) / jnp.sqrt(lens)
    elif pool == "MAX":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        o = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif pool == "FIRST":
        o = x[:, 0]
    elif pool == "LAST":
        idx = jnp.maximum(seq_len - 1, 0)
        o = jnp.take_along_axis(
            x, idx.reshape((n, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    else:
        raise ValueError(f"unknown pooltype {pool}")
    return {"Out": [o], "MaxIndex": [jnp.zeros((n,), jnp.int32)]}


@register_op("sequence_softmax")
def sequence_softmax(ctx, ins, attrs):
    _reject_nested(ins, "sequence_softmax")
    x = first(ins, "X")  # (N, T) or (N, T, 1)
    seq_len = opt_in(ins, "SeqLen")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x.reshape(x.shape[:2]) if squeeze else x
    n, t = v.shape
    if seq_len is None:
        seq_len = jnp.full((n,), t, jnp.int32)
    m = _mask(seq_len, t, jnp.bool_)
    v = jnp.where(m, v, -jnp.inf)
    o = jax.nn.softmax(v, axis=1)
    o = jnp.where(m, o, 0.0)
    if squeeze:
        o = o[..., None]
    return out(Out=o)


@register_op("sequence_expand")
def sequence_expand(ctx, ins, attrs):
    """Expand each row of X to match Y's per-sequence repetition
    (reference sequence_expand_op).  Padded semantics: X (N, D) or
    (N, 1, D) broadcast along Y's time axis.

    Nested Y (reference sequence_expand_op.h lod level 2, ref_level 0):
    when YLen2 is passed (Y is a lod_level=2 batch (N, S1, ...)), each
    X sequence broadcasts across Y's SUB-SEQUENCE slots → nested output
    (N, S1, Tx, ...) whose outer companion is Y's sub-sequence count
    and whose inner companion repeats X's lengths."""
    x, y = first(ins, "X"), first(ins, "Y")
    y_len = opt_in(ins, "YLen")
    y_len2 = opt_in(ins, "YLen2")
    x_len = opt_in(ins, "SeqLen")
    if y_len2 is not None:
        n = x.shape[0]
        s1 = y.shape[1]
        o = jnp.broadcast_to(x[:, None], (n, s1) + x.shape[1:])
        outer = (y_len.astype(jnp.int32) if y_len is not None
                 else jnp.full((n,), s1, jnp.int32))
        if x.ndim == 2:
            # dense per-row vector (N, D): output is a LEVEL-1 sequence
            # of S1 repeated items — no inner level exists
            return {"Out": [o], "Length": [outer]}
        inner = (x_len.astype(jnp.int32) if x_len is not None
                 else jnp.full((n,), x.shape[1], jnp.int32))
        inner2 = jnp.where(jnp.arange(s1)[None, :] < outer[:, None],
                           inner[:, None], 0)
        return {"Out": [o], "Length": [outer], "Length2": [inner2]}
    if x.ndim == y.ndim:
        return out(Out=jnp.broadcast_to(x, y.shape[:2] + x.shape[2:]))
    o = jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])
    return out(Out=o)


@register_op("sequence_expand_as")
def sequence_expand_as(ctx, ins, attrs):
    return sequence_expand(ctx, ins, attrs)


@register_op("sequence_mask")
def sequence_mask(ctx, ins, attrs):
    x = first(ins, "X")  # lengths (N,) or (N,1)
    lens = x.reshape(-1)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask requires static maxlen under XLA")
    from .common import to_jnp_dtype

    dtype = to_jnp_dtype(attrs.get("out_dtype", "int64"))
    m = (jnp.arange(maxlen)[None, :] < lens[:, None]).astype(dtype)
    return {"Y": [m]}


@register_op("sequence_reverse")
def sequence_reverse(ctx, ins, attrs):
    _reject_nested(ins, "sequence_reverse")
    x = first(ins, "X")  # (N, T, ...)
    seq_len = opt_in(ins, "SeqLen")
    n, t = x.shape[0], x.shape[1]
    if seq_len is None:
        return {"Y": [jnp.flip(x, axis=1)]}
    # reverse only the valid prefix of each row
    idx = jnp.arange(t)[None, :]
    rev_idx = jnp.where(idx < seq_len[:, None],
                        seq_len[:, None] - 1 - idx, idx)
    o = jnp.take_along_axis(
        x, rev_idx.reshape((n, t) + (1,) * (x.ndim - 2)), axis=1)
    return {"Y": [o]}


@register_op("sequence_concat")
def sequence_concat(ctx, ins, attrs):
    """Concat CORRESPONDING sequences (reference sequence_concat_op:
    out_i = x1_i ++ x2_i ++ ...), not padded tensors along time.

    Level 1: inputs (N, Tk, ...) with SeqLen list — each output row
    packs every input's valid prefix back-to-back; Length output is the
    summed lengths.  Level 2 (nested): inputs (N, S1k, S2, ...) with
    SeqLen counting sub-sequences — concat along the SUB-SEQUENCE axis
    (reference lod_tensor.h level-0 append); the inner (S2) axis pads
    to the max; Length/Length2 carry the merged companions."""
    xs = ins["X"]
    lens = ins.get("SeqLen")
    lens2 = ins.get("SeqLen2")
    if lens2:
        # nested: concat sub-sequence lists per row
        if lens is None or len(lens) != len(xs):
            raise ValueError("nested sequence_concat needs SeqLen "
                             "(sub-sequence counts) for every input")
        n = xs[0].shape[0]
        s2 = max(x.shape[2] for x in xs)
        xs_p = [jnp.pad(x, [(0, 0), (0, 0), (0, s2 - x.shape[2])] +
                        [(0, 0)] * (x.ndim - 3)) for x in xs]
        total_s1 = sum(x.shape[1] for x in xs)
        o = _pack_rows(xs_p, [l.astype(jnp.int32) for l in lens],
                       total_s1)
        new_len = sum(l.astype(jnp.int32) for l in lens)
        l2 = _pack_rows([jnp.asarray(l2_, jnp.int32) for l2_ in lens2],
                        [l.astype(jnp.int32) for l in lens], total_s1)
        return {"Out": [o], "Length": [new_len], "Length2": [l2]}
    if lens is None or not lens:
        # no ragged info: every row is full length, plain time concat
        return {"Out": [jnp.concatenate(xs, axis=1)],
                "Length": [jnp.full((xs[0].shape[0],),
                                    sum(x.shape[1] for x in xs),
                                    jnp.int32)]}
    if len(lens) != len(xs):
        raise ValueError(
            f"sequence_concat got {len(xs)} inputs but {len(lens)} "
            f"SeqLen companions")
    total_t = sum(x.shape[1] for x in xs)
    lens = [l.astype(jnp.int32) for l in lens]
    o = _pack_rows(xs, lens, total_t)
    return {"Out": [o], "Length": [sum(lens)]}


def _pack_rows(xs, lens, total_t):
    """Per row, place each input's valid prefix back-to-back: output
    position j of row i maps to input k, offset j - starts_k(i) where
    starts are the running sums of that row's lengths."""
    n = xs[0].shape[0]
    starts = [jnp.zeros((n,), jnp.int32)]
    for l in lens[:-1]:
        starts.append(starts[-1] + l)
    pos = jnp.arange(total_t)                      # (T,)
    o = jnp.zeros((n, total_t) + xs[0].shape[2:], xs[0].dtype)
    for k, (x, l, st) in enumerate(zip(xs, lens, starts)):
        # rows of x scatter into [st, st+l)
        rel = pos[None, :] - st[:, None]           # (N, T)
        valid = (rel >= 0) & (rel < l[:, None])
        rel_c = jnp.clip(rel, 0, x.shape[1] - 1)
        gathered = jnp.take_along_axis(
            x, rel_c.reshape((n, total_t) + (1,) * (x.ndim - 2)),
            axis=1)
        o = jnp.where(valid.reshape((n, total_t) + (1,) * (x.ndim - 2)),
                      gathered, o)
    return o


@register_op("sequence_pad")
def sequence_pad(ctx, ins, attrs):
    _reject_nested(ins, "sequence_pad")
    """Already-padded representation: pads/truncates to padded_length."""
    x = first(ins, "X")
    seq_len = opt_in(ins, "SeqLen")
    pad_value = first(ins, "PadValue") if ins.get("PadValue") else None
    padded_length = attrs.get("padded_length", -1)
    n, t = x.shape[0], x.shape[1]
    if seq_len is None:
        seq_len = jnp.full((n,), t, jnp.int32)
    target = padded_length if padded_length and padded_length > 0 else t
    if target > t:
        cfg = [(0, 0), (0, target - t)] + [(0, 0)] * (x.ndim - 2)
        x = jnp.pad(x, cfg)
    elif target < t:
        x = x[:, :target]
    m = _mask(seq_len, target, x.dtype).reshape(
        (n, target) + (1,) * (x.ndim - 2))
    fill = pad_value.reshape(()) if pad_value is not None else 0.0
    o = x * m + fill * (1 - m)
    return {"Out": [o], "Length": [seq_len.astype(jnp.int32)]}


@register_op("sequence_unpad")
def sequence_unpad(ctx, ins, attrs):
    """Inverse of sequence_pad.  Padded world: zero the invalid tail and
    pass lengths through (downstream seq ops mask again)."""
    x = first(ins, "X")
    length = first(ins, "Length").reshape(-1)
    n, t = x.shape[0], x.shape[1]
    m = _mask(length, t, x.dtype).reshape((n, t) + (1,) * (x.ndim - 2))
    return out(Out=x * m)


@register_op("sequence_slice")
def sequence_slice(ctx, ins, attrs):
    x = first(ins, "X")
    offset = first(ins, "Offset").reshape(-1)
    length = first(ins, "Length").reshape(-1)
    n, t = x.shape[0], x.shape[1]
    idx = offset[:, None] + jnp.arange(t)[None, :]
    idx = jnp.clip(idx, 0, t - 1)
    g = jnp.take_along_axis(
        x, idx.reshape((n, t) + (1,) * (x.ndim - 2)), axis=1)
    m = _mask(length, t, x.dtype).reshape((n, t) + (1,) * (x.ndim - 2))
    return out(Out=g * m)


@register_op("sequence_enumerate")
def sequence_enumerate(ctx, ins, attrs):
    x = first(ins, "X")  # (N, T) int ids
    win = attrs["win_size"]
    pad_value = attrs.get("pad_value", 0)
    n, t = x.shape[0], x.shape[1]
    cols = []
    for k in range(win):
        shifted = jnp.pad(x[:, k:], ((0, 0), (0, k)),
                          constant_values=pad_value)
        cols.append(shifted)
    return out(Out=jnp.stack(cols, axis=-1))


@register_op("sequence_erase")
def sequence_erase(ctx, ins, attrs):
    """Mark erased tokens with -1 (static shapes forbid true removal; the
    companion mask/SeqLen convention treats negatives as holes)."""
    x = first(ins, "X")
    tokens = jnp.asarray(attrs.get("tokens", []), dtype=x.dtype)
    if tokens.size == 0:
        return out(Out=x)
    hit = jnp.isin(x, tokens)
    return out(Out=jnp.where(hit, -1, x))


@register_op("sequence_conv")
def sequence_conv(ctx, ins, attrs):
    _reject_nested(ins, "sequence_conv")
    """Window convolution over time (reference sequence_conv_op.cc):
    X (N, T, D), Filter (context_length*D, num_filters)."""
    x = first(ins, "X")
    f = first(ins, "Filter")
    seq_len = opt_in(ins, "SeqLen")
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    n, t, d = x.shape
    if seq_len is not None:
        m = _mask(seq_len, t, x.dtype)[..., None]
        x = x * m
    cols = []
    for k in range(ctx_len):
        off = ctx_start + k
        if off < 0:
            shifted = jnp.pad(x[:, :t + off], ((0, 0), (-off, 0), (0, 0)))
        elif off > 0:
            shifted = jnp.pad(x[:, off:], ((0, 0), (0, off), (0, 0)))
        else:
            shifted = x
        cols.append(shifted)
    im = jnp.concatenate(cols, axis=-1)  # (N, T, ctx_len*D)
    o = im.reshape(n * t, ctx_len * d) @ f
    return out(Out=o.reshape(n, t, -1))


@register_op("im2sequence")
def im2sequence(ctx, ins, attrs):
    """Image → patch sequence (reference im2sequence_op.cc): NCHW →
    (N, num_patches, C*kh*kw)."""
    x = first(ins, "X")
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    ph, pw = attrs.get("paddings", [0, 0, 0, 0])[:2]
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, cd, oh, ow = patches.shape
    o = jnp.transpose(patches.reshape(n, cd, oh * ow), (0, 2, 1))
    return out(Out=o)


@register_op("add_position_encoding")
def add_position_encoding(ctx, ins, attrs):
    x = first(ins, "X")  # (N, T, D)
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    n, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    div = jnp.exp(jnp.arange(0, d, 2, dtype=jnp.float32)
                  * (-jnp.log(10000.0) / d))
    pe = jnp.zeros((t, d), jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(pos * div))
    pe = pe.at[:, 1::2].set(jnp.cos(pos * div[: d // 2]))
    return out(Out=(alpha * x + beta * pe[None]).astype(x.dtype))


@register_op("sequence_scatter")
def sequence_scatter(ctx, ins, attrs):
    """Scatter per-sequence updates into X rows (reference
    sequence_ops/sequence_scatter_op.cc): X (N, D); Ids (N, U) column
    indices with IdsLen (N,) true counts; Updates (N, U).  Out[n, ids] +=
    updates for the first IdsLen[n] entries."""
    x = first(ins, "X")
    ids = first(ins, "Ids").astype(jnp.int32)
    upd = first(ins, "Updates")
    ids_len = opt_in(ins, "IdsLen")
    n, u = ids.shape
    if ids_len is None:
        ids_len = jnp.full((n,), u, jnp.int32)
    else:
        ids_len = ids_len.astype(jnp.int32)
    valid = jnp.arange(u)[None, :] < ids_len[:, None]
    upd = jnp.where(valid, upd, 0.0)
    # padded entries scatter 0 wherever their id points — harmless
    def one(row, i, v):
        return row.at[i].add(v)
    return out(Out=jax.vmap(one)(x, ids, upd))


@register_op("sequence_reshape")
def sequence_reshape(ctx, ins, attrs):
    _reject_nested(ins, "sequence_reshape")
    """Re-chunk each sequence to a new feature width (reference
    sequence_ops/sequence_reshape_op.cc): X (N, T, D) + SeqLen; attr
    new_dim.  Row n's seq_len*D values re-chunk to rows of new_dim:
    out (N, T*D//new_dim, new_dim) with OutLen = seq_len*D//new_dim."""
    x = first(ins, "X")
    seq_len = opt_in(ins, "SeqLen")
    new_dim = int(attrs["new_dim"])
    n, t, d = x.shape
    if (t * d) % new_dim != 0:
        raise ValueError(
            f"sequence_reshape: T*D={t*d} not divisible by new_dim "
            f"{new_dim}")
    if seq_len is None:
        seq_len = jnp.full((n,), t, jnp.int32)
    if (d % new_dim != 0) and (new_dim % d != 0):
        raise ValueError("new_dim must divide or be divisible by D for "
                         "padded re-chunking to preserve row alignment")
    o = x.reshape(n, (t * d) // new_dim, new_dim)
    # ceil: a sequence whose seq_len*D is not new_dim-divisible keeps its
    # tail values in a final partially-padded row instead of silently
    # truncating them (the reference errors per-sequence; static shapes
    # preclude a data-dependent raise here, so no data is dropped)
    out_len = -(-(seq_len.astype(jnp.int32) * d) // new_dim)
    return out(Out=o, OutLen=out_len)


@register_op("lod_reset")
def lod_reset(ctx, ins, attrs):
    """Re-segment a token stream under a new LoD (reference
    lod_reset_op.cc: the underlying rows are kept, only the sequence
    structure is replaced).  The new structure must be STATIC — the attr
    `target_lod` offsets — because it determines the padded output
    shape; a dynamic Y-provided LoD cannot exist under jit (divergence
    note in the layer docstring).

    X is either a plain (R, ...) row stream (each row one token) or a
    padded (N, T, ...) sequence batch with SeqLen, whose valid tokens
    concatenate (in batch order) to the stream being re-segmented."""
    _reject_nested(ins, "lod_reset")
    x = first(ins, "X")
    seq_len = opt_in(ins, "SeqLen")
    target_lod = [int(v) for v in attrs["target_lod"]]
    if len(target_lod) < 2 or target_lod[0] != 0:
        raise ValueError(f"target_lod must start at 0 with >=2 offsets, "
                         f"got {target_lod}")
    new_lens = [target_lod[i + 1] - target_lod[i]
                for i in range(len(target_lod) - 1)]
    if any(l < 0 for l in new_lens):
        raise ValueError(f"target_lod must be non-decreasing: {target_lod}")
    num_new, max_new = len(new_lens), max(new_lens)
    total = target_lod[-1]

    # flat token index t -> source position
    t_idx = jnp.arange(total)
    if seq_len is None:
        # rows ARE the stream; the new lod must span exactly the rows
        # (reference lod_reset_op.cc InferShape enforces the same)
        if total != x.shape[0]:
            raise ValueError(
                f"lod_reset: target_lod covers {total} rows but X has "
                f"{x.shape[0]}")
        gathered = x[t_idx]
    else:
        lens = seq_len.astype(jnp.int32)
        starts = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                  jnp.cumsum(lens)])[:-1]
        # row owning token t: last n with starts[n] <= t
        n_of = jnp.sum(t_idx[:, None] >= (starts + lens)[None, :],
                       axis=1)
        n_of = jnp.clip(n_of, 0, x.shape[0] - 1)
        pos = jnp.clip(t_idx - starts[n_of], 0, x.shape[1] - 1)
        gathered = x[n_of, pos]

    # scatter the stream into the new padded layout
    out_shape = (num_new, max_new) + x.shape[(1 if seq_len is None
                                              else 2):]
    o = jnp.zeros(out_shape, x.dtype)
    seq_of = jnp.searchsorted(jnp.asarray(target_lod[1:]), t_idx,
                              side="right")
    pos_new = t_idx - jnp.asarray(target_lod)[seq_of]
    o = o.at[seq_of, pos_new].set(gathered)
    return {"Out": [o],
            "Length": [jnp.asarray(new_lens, jnp.int32)]}

@register_op("lod_rank_table")
def lod_rank_table(ctx, ins, attrs):
    """Rank a batch of sequences by length, DESCENDING, ties kept in
    batch order — the dense analog of the reference LoDRankTable
    (operators/lod_rank_table_op.cc:19; its items are (index, length)
    sorted by length desc).  Here the table IS the (B,) int32 index
    vector; lengths come from the input's .seq_len companion."""
    _reject_nested(ins, "lod_rank_table")
    sl = opt_in(ins, "SeqLen")
    if sl is None:
        raise ValueError(
            "lod_rank_table requires a level-1 sequence input "
            "(a var with a .seq_len companion)")
    # jnp.argsort is stable: equal lengths keep original batch order,
    # matching the reference's std::stable_sort
    order = jnp.argsort(-sl.astype(jnp.int32))
    return out(Out=order.astype(jnp.int32))


@register_op("reorder_lod_tensor_by_rank")
def reorder_lod_tensor_by_rank(ctx, ins, attrs):
    """Permute the batch dim of X by a rank table
    (operators/reorder_lod_tensor_by_rank_op.cc:34).  Differentiable
    (gather transposes to scatter-add); the layer wrapper reorders the
    .seq_len companion alongside via the OutSeqLen output."""
    _reject_nested(ins, "reorder_lod_tensor_by_rank")
    x = first(ins, "X")
    rt = first(ins, "RankTable").astype(jnp.int32)
    outs = {"Out": [jnp.take(x, rt, axis=0)]}
    sl = opt_in(ins, "SeqLen")
    if sl is not None:
        outs["OutSeqLen"] = [jnp.take(sl, rt, axis=0)]
    return outs
