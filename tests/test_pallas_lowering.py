"""Cross-platform TPU lowering of the Pallas kernels.

The CPU test suite exercises these kernels through the Pallas
INTERPRETER, which proves numerics but not that the kernel IR lowers
for the real TPU target (r4 finding: interpreter != Mosaic).
jax.export with platforms=["tpu"] runs the actual Pallas->Mosaic
lowering rules on any host, so block-spec/primitive errors surface
here instead of on the first chip contact.  (The Mosaic->LLO compile
itself still happens on hardware — this pins everything before it.)
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _export_tpu(fn, *args):
    """Export for the TPU target with the interpret gate overridden —
    otherwise the CPU host would serialize the INTERPRETER path and
    the check would be vacuous."""
    from paddle_tpu.ops.pallas import force_mosaic_lowering

    with force_mosaic_lowering():
        exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    # prove the Mosaic custom call is actually in the artifact
    mlir = exp.mlir_module()
    assert "tpu_custom_call" in mlir, \
        "export did not contain the Mosaic kernel (interpreter path?)"
    return exp


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 4, 256, 64), jnp.float32)
    return mk(), mk(), mk()


def test_flash_attention_fwd_lowers_for_tpu(qkv):
    from paddle_tpu.ops.pallas.flash_attention import \
        pallas_flash_attention

    q, k, v = qkv
    exp = _export_tpu(
        lambda q, k, v: pallas_flash_attention(q, k, v, None, 0.125,
                                               True), q, k, v)
    assert len(exp.mlir_module_serialized) > 0
    assert "tpu" in exp.platforms


def test_flash_attention_bwd_lowers_for_tpu(qkv):
    from paddle_tpu.ops.pallas.flash_attention import \
        pallas_flash_attention

    q, k, v = qkv

    def loss(q, k, v):
        return jnp.sum(
            pallas_flash_attention(q, k, v, None, 0.125, True) ** 2)

    exp = _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)
    assert len(exp.mlir_module_serialized) > 0


def test_vocab_ce_fwd_and_bwd_lower_for_tpu():
    from paddle_tpu.ops.pallas.vocab_ce import fused_vocab_ce

    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(8, 128, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256, 4096) * 0.02, jnp.float32)
    lbl = jnp.asarray(rng.randint(0, 4096, (8, 128)), jnp.int32)

    def loss(h, w):
        return jnp.sum(fused_vocab_ce(h, w, lbl, 0.1, 1024, 2048))

    assert len(_export_tpu(loss, h, w).mlir_module_serialized) > 0
    assert len(_export_tpu(jax.grad(loss, argnums=(0, 1)), h,
                           w).mlir_module_serialized) > 0
