"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle
Fluid 1.x capabilities.

Public API mirrors `paddle.fluid` (reference: python/paddle/fluid/
__init__.py): Program/Block/Variable graph building, layers, optimizers,
Executor, ParallelExecutor/CompiledProgram, io, readers — but the runtime
is JAX/XLA: programs compile to single fused TPU computations, parallelism
is pjit/GSPMD over a device Mesh, and kernels are jnp/lax/Pallas.
"""

from . import amp  # noqa: F401
from . import clip  # noqa: F401
from . import initializer  # noqa: F401
from . import layers  # noqa: F401
from . import ops as _ops  # registers all op impls  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from .core import unique_name  # noqa: F401
from .core.backward import append_backward, gradients  # noqa: F401
from .core.executor import (Executor, Scope, global_scope,  # noqa: F401
                            scope_guard)
from .core.program import (Block, Operator, Parameter, Program,  # noqa: F401
                           Variable, default_main_program,
                           default_startup_program, name_scope,
                           pipeline_scope, pipeline_segment,
                           program_guard, recompute_scope)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from . import nets  # noqa: F401
from . import parallel  # noqa: F401
from .parallel.compiler import (BuildStrategy, CompiledProgram,  # noqa: F401
                                ExecutionStrategy)
from .parallel.parallel_executor import ParallelExecutor  # noqa: F401
from . import io  # noqa: F401
from . import inference  # noqa: F401
from . import quantize as quantize_module  # noqa: F401
from .inference import (AnalysisConfig, Predictor,  # noqa: F401
                        create_paddle_predictor)
from .quantize import QuantizeTranspiler  # noqa: F401
from . import data  # noqa: F401
from . import contrib  # noqa: F401
from .async_executor import AsyncExecutor  # noqa: F401
from .data.data_feed import DataFeedDesc  # noqa: F401
from . import debugger  # noqa: F401
from . import imperative  # noqa: F401
from . import evaluator  # noqa: F401
from . import metrics  # noqa: F401
from . import observe  # noqa: F401
from . import resilience  # noqa: F401
from . import serving  # noqa: F401
from . import profiler  # noqa: F401
from .data.data_feeder import DataFeeder  # noqa: F401
from .flags import FLAGS  # noqa: F401


class CPUPlace:
    """Placement token (reference: paddle/fluid/platform/place.h:26-57).
    Device choice on TPU is driven by the JAX platform / shardings, so
    Places are identity tokens for API parity."""

    def __repr__(self):
        return "CPUPlace()"


class TPUPlace:
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# Alias so fluid scripts using CUDAPlace run unchanged on TPU.
CUDAPlace = TPUPlace


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


__version__ = "0.1.0"
